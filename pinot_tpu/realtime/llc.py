"""LLC (low-level consumer) realtime coordination.

Mirrors the reference's three-way dance (SURVEY §3.4):

- server: ``LLRealtimeSegmentDataManager.java:68`` — one consumer per
  stream partition appends into a mutable segment until a row/time
  threshold, then reports ``segmentConsumed(offset)`` to the controller.
- controller: ``SegmentCompletionManager.java:45-54`` — an FSM per
  consuming segment (HOLDING -> COMMITTER_DECIDED -> COMMITTER_UPLOADING
  -> COMMITTED) picks the max-offset replica as committer and answers
  each replica HOLD / CATCH_UP / COMMIT / KEEP / DISCARD / NOT_LEADER
  (``SegmentCompletionProtocol.java:63-105``).
- commit: the committer converts mutable -> immutable columnar, uploads;
  the controller persists metadata (exact start/end offsets — the
  checkpoint), flips replicas CONSUMING -> ONLINE (laggards download the
  committed copy), and opens the next CONSUMING segment at the end
  offset.  Restart resumes from the last committed end offset
  (``ValidationManager`` repairs missing consuming segments).

Segment naming: ``{table}__{partition}__{seq}`` (LLCSegmentName analog).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.common.fencing import StaleEpochError, epoch_int
from pinot_tpu.common.schema import Schema, time_unit_to_millis
from pinot_tpu.common.tableconfig import StreamConfig, TableConfig
from pinot_tpu.controller.resource_manager import (
    CONSUMING,
    ClusterResourceManager,
    ONLINE,
)
from pinot_tpu.realtime.mutable import MutableSegment
from pinot_tpu.realtime.stream import StreamProvider

logger = logging.getLogger(__name__)

MAX_HOLD_TIME_MS = 3000  # SegmentCompletionProtocol.java:50


def _commit_stall_ms() -> float:
    """How long an elected committer may go protocol-silent (no
    segmentConsumed/segmentCommit calls) before the FSM re-elects a
    caught-up replica (the reference's max-segment-commit-time,
    ``controller.realtime.segment.commit.timeoutSeconds``).  Lease
    validity alone cannot catch this: under a ONE-WAY partition the
    victim's heartbeats keep renewing its controller-side lease while
    its self-fenced commit plane is frozen."""
    return float(os.environ.get("PINOT_TPU_COMMIT_STALL_S", "120")) * 1000.0

# FSM states (SegmentCompletionManager.java:48-54)
HOLDING = "HOLDING"
COMMITTER_DECIDED = "COMMITTER_DECIDED"
COMMITTER_UPLOADING = "COMMITTER_UPLOADING"
COMMITTED = "COMMITTED"

# responses (SegmentCompletionProtocol.java:63-105)
RESP_HOLD = "HOLD"
RESP_CATCH_UP = "CATCH_UP"
RESP_DISCARD = "DISCARD"
RESP_KEEP = "KEEP"
RESP_COMMIT = "COMMIT"
RESP_NOT_LEADER = "NOT_LEADER"


def make_segment_name(table: str, partition: int, seq: int) -> str:
    return f"{table}__{partition}__{seq}"


def parse_segment_name(name: str) -> Tuple[str, int, int]:
    table, partition, seq = name.rsplit("__", 2)
    return table, int(partition), int(seq)


class _SegmentFsm:
    def __init__(self, num_replicas: int) -> None:
        self.state = HOLDING
        self.num_replicas = num_replicas
        self.offsets: Dict[str, int] = {}
        self.committer: Optional[str] = None
        self.target_offset: Optional[int] = None
        self.final_offset: Optional[int] = None
        self.first_report_ms: Optional[float] = None
        self.commit_inflight = False  # an upload is being persisted
        # last protocol call from the elected committer (stall detector)
        self.committer_activity_ms: Optional[float] = None


class SegmentCompletionManager:
    """Controller-side commit FSM (SegmentCompletionManager.java:45).

    Partition fencing: every protocol call may carry the caller's
    serving-lease ``epoch`` (the controller incarnation that granted
    it); a mismatch against this controller's epoch raises a typed
    ``StaleEpochError`` — a committer leased by a dead controller
    cannot commit into a live one, and a zombie controller cannot
    accept commits leased by its successor.  ``lease_checker`` (wired
    by the Controller to ``ParticipantGateway.server_lease_valid``)
    lets the FSM re-elect when the chosen committer's lease expires
    mid-protocol (partitioned away mid-upload) instead of holding the
    partition's commit hostage forever; the commit-stall window
    (``PINOT_TPU_COMMIT_STALL_S``) re-elects a committer whose lease
    stays controller-side valid but whose commit plane went silent
    (one-way partition: heartbeats arrive, replies are lost)."""

    def __init__(self, realtime_manager: "RealtimeSegmentManager") -> None:
        self.rm = realtime_manager
        self._fsm: Dict[str, _SegmentFsm] = {}
        self._lock = threading.Lock()
        # (server) -> bool: does this replica still hold a valid
        # serving lease?  None = no lease plane (in-process harness).
        self.lease_checker = None
        self.commit_stall_ms = _commit_stall_ms()
        self.clock = time.time  # injectable for stall/hold tests

    def _get(self, segment: str) -> _SegmentFsm:
        fsm = self._fsm.get(segment)
        if fsm is None:
            replicas = self.rm.resources.get_ideal_state(
                self.rm.physical_table_of(segment)
            ).get(segment, {})
            fsm = _SegmentFsm(max(len(replicas), 1))
            self._fsm[segment] = fsm
        return fsm

    def _mark(self, name: str) -> None:
        metrics = getattr(self.rm, "metrics", None)
        if metrics is not None:
            metrics.meter(name).mark()

    def _check_epoch(self, epoch) -> None:
        """Reject a protocol call fenced off by controller failover.
        Unarmed when either side has no epoch (legacy / in-process)."""
        current = getattr(self.rm, "epoch", None)
        if current is None or epoch is None:
            return
        e = epoch_int(epoch)
        if e == -1:
            return
        if e != int(current):
            self._mark("fence.staleEpochRejections")
            # direction-aware message (fields keep their wire meaning:
            # staleEpoch = caller's, currentEpoch = this controller's):
            # an operator debugging the 409 must be pointed at the side
            # that is actually fenced off
            if e < int(current):
                msg = (
                    f"commit-plane call under stale lease epoch {e}; "
                    f"controller epoch is {current}"
                )
            else:
                msg = (
                    f"commit-plane call under lease epoch {e} from a "
                    f"newer controller incarnation; this controller "
                    f"(epoch {current}) is the fenced-off zombie"
                )
            raise StaleEpochError(msg, stale=e, current=int(current))

    def _committer_leased(self, fsm: _SegmentFsm) -> bool:
        if self.lease_checker is None or fsm.committer is None:
            return True
        try:
            return bool(self.lease_checker(fsm.committer))
        except Exception:  # a broken probe must not wedge the protocol
            return True

    def segment_consumed(
        self, segment: str, server: str, offset: int, epoch=None
    ) -> Tuple[str, Optional[int]]:
        """A replica hit its threshold at ``offset``. Returns
        (response, target_offset)."""
        self._check_epoch(epoch)
        with self._lock:
            fsm = self._get(segment)
            now = self.clock() * 1000

            if fsm.state == COMMITTED:
                if offset == fsm.final_offset:
                    return RESP_KEEP, fsm.final_offset
                return RESP_DISCARD, fsm.final_offset

            fsm.offsets[server] = offset
            if fsm.first_report_ms is None:
                fsm.first_report_ms = now

            if fsm.state == HOLDING:
                all_reported = len(fsm.offsets) >= fsm.num_replicas
                hold_expired = now - fsm.first_report_ms > MAX_HOLD_TIME_MS
                if not (all_reported or hold_expired):
                    return RESP_HOLD, None
                # decide committer: max offset wins (ties -> name order)
                fsm.committer = max(fsm.offsets, key=lambda s: (fsm.offsets[s], s))
                fsm.target_offset = fsm.offsets[fsm.committer]
                fsm.committer_activity_ms = now
                fsm.state = COMMITTER_DECIDED

            if fsm.state in (COMMITTER_DECIDED, COMMITTER_UPLOADING):
                assert fsm.target_offset is not None
                if server == fsm.committer:
                    fsm.committer_activity_ms = now
                if offset < fsm.target_offset:
                    return RESP_CATCH_UP, fsm.target_offset
                stalled = (
                    fsm.committer_activity_ms is not None
                    and now - fsm.committer_activity_ms > self.commit_stall_ms
                )
                if (
                    server != fsm.committer
                    and not fsm.commit_inflight
                    and (stalled or not self._committer_leased(fsm))
                ):
                    # committer failover: the elected committer's
                    # serving lease expired (partitioned away / died
                    # mid-upload), OR it went protocol-silent past the
                    # commit-stall window — under a ONE-WAY partition
                    # its heartbeats keep the controller-side lease
                    # alive while its self-fenced commit plane freezes,
                    # so lease validity alone cannot detect it.  No
                    # upload is being persisted — re-elect this
                    # caught-up replica.  The old committer's late
                    # segmentCommit lands on ``committer != server``
                    # below: NOT_LEADER, no double commit.
                    logger.warning(
                        "committer %s for %s %s; re-electing %s",
                        fsm.committer, segment,
                        "stalled past the commit window" if stalled
                        else "lost its lease",
                        server,
                    )
                    self._mark("fence.committerReElections")
                    fsm.committer = server
                    fsm.committer_activity_ms = now
                    fsm.state = COMMITTER_DECIDED
                if server == fsm.committer and not fsm.commit_inflight:
                    # COMMITTER_UPLOADING here (not inflight) means a
                    # previous commit attempt FAILED (e.g. the
                    # controller had just restarted): re-issue COMMIT so
                    # the committer retries instead of holding forever.
                    # While an upload is actually being persisted the
                    # committer holds — no duplicate commit.
                    fsm.state = COMMITTER_UPLOADING
                    return RESP_COMMIT, fsm.target_offset
                return RESP_HOLD, fsm.target_offset
        return RESP_HOLD, None

    def commit_fence_check(self, segment: str, server: str, epoch=None):
        """Cheap pre-upload fence: raises the typed ``StaleEpochError``
        or returns ``NOT_LEADER`` for a caller with no write authority,
        so the HTTP surface can reject a fenced upload before buffering
        and parsing megabytes of segment body.  Advisory only — the
        authoritative fences re-run under the lock in
        ``segment_commit`` (a lease can expire between the two)."""
        self._check_epoch(epoch)
        with self._lock:
            fsm = self._fsm.get(segment)
            if fsm is not None and fsm.committer == server:
                # upload starting: the body transfer that follows can
                # legitimately outlast the commit-stall window — stamp
                # activity NOW so a slow upload isn't mistaken for a
                # silent (partitioned) committer and re-elected away
                fsm.committer_activity_ms = self.clock() * 1000
        if self.lease_checker is not None:
            try:
                leased = bool(self.lease_checker(server))
            except Exception:
                leased = True
            if not leased:
                self._mark("fence.leaseRejections")
                return RESP_NOT_LEADER
        return None

    def segment_commit(self, segment: str, server: str, committed, epoch=None) -> str:
        """Committer uploads its converted segment (segmentCommit).

        The FSM flips to COMMITTED only AFTER the metadata/ideal-state
        persistence succeeds — a failure (controller freshly restarted,
        replica not re-registered yet) leaves the FSM in
        COMMITTER_UPLOADING so the committer's next segmentConsumed
        retries the commit rather than wedging on KEEP/HOLD.

        Fencing order: stale epoch raises (typed), an expired lease is
        NOT_LEADER (the replica may retry after renewing), and a
        non-committer is NOT_LEADER — so a committer partitioned away
        mid-upload can never land a second copy after re-election.
        """
        self._check_epoch(epoch)
        with self._lock:
            fsm = self._get(segment)
            if server == fsm.committer:
                fsm.committer_activity_ms = self.clock() * 1000
            if self.lease_checker is not None:
                try:
                    leased = bool(self.lease_checker(server))
                except Exception:
                    leased = True
                if not leased:
                    # lease fence FIRST (even over the COMMITTED
                    # short-circuit): an upload arriving without write
                    # authority is always rejected — the replica must
                    # renew its lease and learn the final verdict via
                    # segmentConsumed (KEEP/DISCARD) instead
                    self._mark("fence.leaseRejections")
                    return RESP_NOT_LEADER
            if fsm.state == COMMITTED:
                return RESP_KEEP  # duplicate upload after a lost reply
            if fsm.committer != server or fsm.state != COMMITTER_UPLOADING:
                return RESP_NOT_LEADER
            if fsm.commit_inflight:
                # a previous upload of this segment is still being
                # persisted (slow request + client retry): hold rather
                # than run on_segment_committed twice concurrently
                return RESP_HOLD
            fsm.commit_inflight = True
        try:
            self.rm.on_segment_committed(segment, committed)
        except Exception:
            with self._lock:
                fsm.commit_inflight = False
            raise
        with self._lock:
            fsm.commit_inflight = False
            fsm.state = COMMITTED
            fsm.final_offset = fsm.target_offset
        return RESP_KEEP


class RealtimeSegmentManager:
    """Controller-side realtime coordinator
    (PinotLLCRealtimeSegmentManager analog): creates CONSUMING segments,
    persists commit metadata, opens the next sequence."""

    def __init__(self, resources: ClusterResourceManager, store, metrics=None) -> None:
        self.resources = resources
        self.store = store
        # optional ControllerMetrics: realtime commit-plane series
        # (segmentCommits meter + segmentCommitMs persistence timer)
        self.metrics = metrics
        # optional IngestConsumerPool (realtime/pool.py): when set,
        # every in-process consumer this manager creates is driven by
        # the pool's bounded workers instead of waiting for manual
        # consume_step calls — the partition-parallel ingest plane
        self.ingest_pool = None
        # controller fencing incarnation (set by the Controller): arms
        # the commit-plane epoch fence in SegmentCompletionManager
        self.epoch: Optional[int] = None
        if metrics is not None:
            metrics.meter("segmentCommits")
            metrics.timer("segmentCommitMs")
        self.completion = SegmentCompletionManager(self)
        self._tables: Dict[str, Dict[str, Any]] = {}  # physical -> {schema, stream, config}
        self._consumers: Dict[Tuple[str, str], "RealtimeSegmentDataManager"] = {}
        self._lock = threading.Lock()
        # serializes consuming-segment creation: commit-time creation,
        # the periodic ValidationManager tick, and the server-available
        # repair kick can all race the check-then-create otherwise
        self._create_lock = threading.Lock()

    # -- setup ---------------------------------------------------------
    def setup_table(
        self, config: TableConfig, schema: Schema, stream: StreamProvider
    ) -> str:
        physical = self.resources.add_table(config)
        with self._lock:
            self._tables[physical] = {
                "schema": schema,
                "stream": stream,
                "config": config,
            }
        if self.resources.property_store is not None:
            from pinot_tpu.realtime.stream import describe_stream

            desc = describe_stream(stream)
            if desc is not None:
                self.resources.property_store.put("streams", physical, desc)
        if config.stream is not None and config.stream.consumer_type == "highlevel":
            # HLC: one consumer per SERVER (not per partition) in a
            # broker-coordinated group; segments are server-owned and
            # roll locally (HLRealtimeSegmentDataManager.java:54)
            self.ensure_hlc_consumers(physical)
        else:
            for partition in range(stream.partition_count()):
                self._create_consuming_segment(physical, partition, seq=0, start_offset=0)
        return physical

    def update_schema(self, raw_name: str, schema: Schema) -> List[str]:
        """Schema evolution for realtime tables: swap the stored schema
        so the NEXT segment rollover consumes with the grown schema
        (CONSUMING transitions serialize it as schemaJson).  The
        currently-consuming segment keeps its frozen schema — its rows
        get default columns when it seals, matching the reference's
        apply-at-rollover behavior."""
        updated = []
        with self._lock:
            for physical, tinfo in self._tables.items():
                if tinfo["config"].raw_name == raw_name:
                    tinfo["schema"] = schema
                    updated.append(physical)
        return updated

    def _is_hlc(self, physical: str) -> bool:
        with self._lock:
            tinfo = self._tables.get(physical)
        return bool(
            tinfo
            and tinfo["config"].stream is not None
            and tinfo["config"].stream.consumer_type == "highlevel"
        )

    def ensure_hlc_consumers(self, physical: str) -> None:
        """Every live server gets one CONSUMING segment for an HLC
        table (new servers join the group when they register — the
        server-available repair hook calls this too)."""
        if not self._is_hlc(physical):
            return
        with self.resources._lock:
            live = sorted(
                name
                for name, inst in self.resources.instances.items()
                if inst.role == "server" and inst.alive and not inst.draining
            )
        ideal = self.resources.get_ideal_state(physical)
        # ownership from the pinned replica sets (sealed uploads replace
        # segment metadata, so custom keys are NOT a reliable record);
        # track the highest seq per idx so recreated consumers never
        # collide with a historical sealed segment name
        owners = set()
        max_seq: Dict[int, int] = {}
        idx_last: Dict[int, set] = {}  # replica set of the newest segment per idx
        consuming_idx = set()
        for seg, replicas in ideal.items():
            try:
                _, idx, seq = parse_segment_name(seg)
            except ValueError:
                continue
            if seq > max_seq.get(idx, -1):
                max_seq[idx] = seq
                idx_last[idx] = set(replicas)
            if CONSUMING in replicas.values():
                owners.update(replicas)
                consuming_idx.add(idx)
        next_idx = 0
        for server in live:
            if server in owners:
                continue
            # Mid-roll (sealed upload flipped the entry ONLINE before the
            # roll registered the successor) or crash-after-seal: the
            # server still owns the idx whose newest segment is pinned to
            # it.  Continue that idx at the next sequence — the name
            # matches what the server's own /realtime/hlc/roll would
            # register, so both paths dedupe instead of this tick opening
            # a phantom CONSUMING segment at a fresh idx that no consumer
            # will ever serve.
            resumed = False
            for idx in sorted(max_seq):
                if idx not in consuming_idx and server in idx_last.get(idx, ()):
                    self._create_hlc_segment(
                        physical, server, idx, seq=max_seq[idx] + 1
                    )
                    # mark the idx consumed so a second live server in
                    # the same replica set (replication > 1 after a
                    # rebalance) doesn't no-op on the deduped name and
                    # end the tick with no CONSUMING segment at all —
                    # it falls through to a fresh idx instead
                    max_seq[idx] += 1
                    consuming_idx.add(idx)
                    resumed = True
                    break
            if resumed:
                continue
            while next_idx in max_seq:
                next_idx += 1
            max_seq[next_idx] = -1
            self._create_hlc_segment(
                physical, server, next_idx, seq=max_seq[next_idx] + 1
            )

    def register_hlc_roll(self, physical: str, server: str, idx: int, seq: int) -> str:
        """A server sealed its HLC segment and continues locally on the
        next sequence: record the new CONSUMING segment so routing
        covers it (the server already serves it)."""
        if not self._is_hlc(physical):
            raise ValueError(f"{physical} is not a highlevel-consumer table")
        return self._create_hlc_segment(physical, server, idx, seq)

    def _create_hlc_segment(self, physical: str, server: str, idx: int, seq: int) -> str:
        from pinot_tpu.segment.immutable import SegmentMetadata

        name = make_segment_name(physical, idx, seq)
        with self._create_lock:
            if name in self.resources.get_ideal_state(physical):
                return name
            with self._lock:
                tinfo = self._tables.get(physical)
            from pinot_tpu.realtime.stream import describe_stream

            desc = describe_stream(tinfo["stream"]) if tinfo else None
            meta = SegmentMetadata(
                segment_name=name,
                table_name=physical,
                num_docs=0,
                custom={
                    "partition": idx,
                    "seq": seq,
                    "hlcServer": server,
                    "status": "IN_PROGRESS",
                },
            )
            info: Dict[str, Any] = {
                "partition": idx,
                "startOffset": 0,
                "consumerType": "highlevel",
                "hlcServer": server,
            }
            if desc is not None:
                info["streamDescriptor"] = desc
            if tinfo is not None:
                info["rowsPerSegment"] = (
                    tinfo["config"].stream.rows_per_segment
                    if tinfo["config"].stream
                    else 100_000
                )
                info["schemaJson"] = tinfo["schema"].to_json()
            self.resources.add_segment(
                physical, meta, info, target_state=CONSUMING, servers=[server]
            )
            return name

    def recover_table(self, physical: str, config: TableConfig, schema: Schema) -> bool:
        """Rebuild the in-memory realtime wiring for a table restored
        from the property store: reattach the stream provider and put
        ``consuming_starter`` callbacks back on every CONSUMING
        segment's metadata record so re-registering servers resume
        consumption from the checkpointed offsets (the reference
        resumes from the per-segment ZK offsets on restart, SURVEY §5
        checkpoint/resume)."""
        store = self.resources.property_store
        if store is None:
            return False
        desc = store.get("streams", physical)
        if desc is None:
            return False
        from pinot_tpu.realtime.stream import stream_from_descriptor

        stream = stream_from_descriptor(desc)
        with self._lock:
            self._tables[physical] = {
                "schema": schema,
                "stream": stream,
                "config": config,
            }
        with self.resources._lock:
            for (tbl, seg), info in self.resources.segment_metadata.items():
                if tbl != physical:
                    continue
                replicas = self.resources.ideal_states.get(physical, {}).get(seg, {})
                if CONSUMING in replicas.values():
                    info["consuming_starter"] = self._start_consumer
        return True

    def physical_table_of(self, segment: str) -> str:
        return parse_segment_name(segment)[0]

    def _create_consuming_segment(
        self, physical: str, partition: int, seq: int, start_offset: int
    ) -> str:
        name = make_segment_name(physical, partition, seq)
        with self._create_lock:
            if name in self.resources.get_ideal_state(physical):
                return name  # idempotent: a concurrent path created it
            return self._create_consuming_segment_locked(
                physical, partition, seq, start_offset, name
            )

    def _create_consuming_segment_locked(
        self, physical: str, partition: int, seq: int, start_offset: int, name: str
    ) -> str:
        from pinot_tpu.segment.immutable import SegmentMetadata

        meta = SegmentMetadata(
            segment_name=name,
            table_name=physical,
            num_docs=0,
            custom={
                "partition": partition,
                "seq": seq,
                "startOffset": start_offset,
                "status": "IN_PROGRESS",
            },
        )
        info: Dict[str, Any] = {
            "consuming_starter": self._start_consumer,
            "partition": partition,
            "startOffset": start_offset,
        }
        # serializable consume spec: lets REMOTE participants (separate
        # server processes) start a consumer from the transition message
        # alone, and survives in the property store for recovery
        with self._lock:
            tinfo = self._tables.get(physical)
        if tinfo is not None:
            from pinot_tpu.realtime.stream import describe_stream

            desc = describe_stream(tinfo["stream"])
            if desc is not None:
                info["streamDescriptor"] = desc
            info["rowsPerSegment"] = (
                tinfo["config"].stream.rows_per_segment
                if tinfo["config"].stream
                else 100_000
            )
            info["schemaJson"] = tinfo["schema"].to_json()
        self.resources.add_segment(
            physical,
            meta,
            info,
            target_state=CONSUMING,
        )
        return name

    # -- server-side consumer creation (via ServerStarter CONSUMING) --
    def _start_consumer(self, server_instance, table: str, segment: str, info: Dict[str, Any]) -> bool:
        if info.get("consumerType") == "highlevel":
            # HLC consumers live in networked server processes (the
            # group coordinator is the stream broker); the in-process
            # harness supports LLC tables only
            logger.warning("in-process cluster cannot host HLC consumer %s", segment)
            return False
        with self._lock:
            tinfo = self._tables.get(table)
            if (segment, server_instance.name) in self._consumers:
                return True  # already consuming; don't reset the offset
        if tinfo is None:
            return False
        dm = RealtimeSegmentDataManager(
            server=server_instance,
            manager=self,
            table=table,
            segment_name=segment,
            schema=tinfo["schema"],
            stream=tinfo["stream"],
            partition=int(info["partition"]),
            start_offset=int(info["startOffset"]),
            rows_per_segment=tinfo["config"].stream.rows_per_segment
            if tinfo["config"].stream
            else 100_000,
        )
        with self._lock:
            self._consumers[(segment, server_instance.name)] = dm
        server_instance.add_segment(table, dm.mutable)
        pool = self.ingest_pool
        if pool is not None:
            pool.add(dm, key=(segment, server_instance.name))
        return True

    def consumers_of(self, segment: str) -> List["RealtimeSegmentDataManager"]:
        with self._lock:
            return [dm for (seg, _), dm in self._consumers.items() if seg == segment]

    def release_segment_consumers(self, segment: str, server: Optional[str] = None) -> None:
        """Stop and forget in-process consumers of ``segment`` — all of
        them, or only ``server``'s (the stabilizer retires a consuming
        segment whose holders are all dead/draining, or sheds one
        unavailable replica of a still-consuming segment; a stale map
        entry would make a later CONSUMING transition on the same
        (segment, server) resume the OLD mutable with uncommitted rows
        instead of re-consuming from the committed offset)."""
        with self._lock:
            for key in [
                k
                for k in self._consumers
                if k[0] == segment and (server is None or k[1] == server)
            ]:
                self._consumers[key].stop()
                del self._consumers[key]
                if self.ingest_pool is not None:
                    self.ingest_pool.remove(key)

    # -- commit --------------------------------------------------------
    def on_segment_committed(self, segment: str, committed) -> None:
        t0 = time.perf_counter()
        physical, partition, seq = parse_segment_name(segment)
        path = self.store.save(physical, committed)
        end_offset = committed.metadata.custom.get("endOffset", 0)
        # persist metadata (the ZK offset checkpoint) + flip replicas ONLINE
        with self.resources._lock:
            self.resources.segment_metadata[(physical, segment)] = {
                "metadata": committed.metadata,
                "dir": path,
                "segment": committed,
            }
            replicas = self.resources.ideal_states[physical].get(segment, {})
            for server in replicas:
                replicas[server] = ONLINE
        self.resources.persist_ideal_state(physical)
        self.resources.persist_segment_record(physical, segment)
        for server in list(replicas):
            self.resources._execute_transition(physical, segment, server, ONLINE)
        self.resources._notify_view(physical)
        # retire consumers for this segment
        with self._lock:
            for key in [k for k in self._consumers if k[0] == segment]:
                self._consumers[key].stop()
                del self._consumers[key]
                if self.ingest_pool is not None:
                    self.ingest_pool.remove(key)
        if self.metrics is not None:
            self.metrics.meter("segmentCommits").mark()
            self.metrics.timer("segmentCommitMs").update(
                (time.perf_counter() - t0) * 1000
            )
        # open the next consuming segment at the committed end offset;
        # a transient failure (no replica re-registered yet after a
        # controller restart) must NOT fail the commit itself — the
        # ValidationManager recreates missing CONSUMING segments
        # (ensure_consuming_segments, ValidationManager.java:64)
        try:
            self._create_consuming_segment(physical, partition, seq + 1, int(end_offset))
        except Exception as e:
            logger.warning(
                "could not open next consuming segment for %s partition %d "
                "(validation repair will retry): %s",
                physical,
                partition,
                e,
            )

    # -- validation hook ----------------------------------------------
    def ensure_consuming_segments(self) -> None:
        """Re-create missing CONSUMING segments
        (ValidationManager.java:64 LLC repair)."""
        with self._lock:
            tables = list(self._tables.keys())
        for physical in tables:
            if self._is_hlc(physical):
                # HLC repair: every live server must be consuming
                self.ensure_hlc_consumers(physical)
                continue
            ideal = self.resources.get_ideal_state(physical)
            with self._lock:
                stream = self._tables[physical]["stream"]
            for partition in range(stream.partition_count()):
                has_consuming = False
                max_seq, max_end = -1, 0
                for seg, replicas in ideal.items():
                    try:
                        _, p, seq = parse_segment_name(seg)
                    except ValueError:
                        continue
                    if p != partition:
                        continue
                    if any(st == CONSUMING for st in replicas.values()):
                        has_consuming = True
                    info = self.resources.get_segment_metadata(physical, seg)
                    if info and info.get("metadata") is not None and seq > max_seq:
                        max_seq = seq
                        max_end = int(info["metadata"].custom.get("endOffset", 0))
                if not has_consuming:
                    logger.info(
                        "validation: recreating consuming segment %s p%d seq%d @%d",
                        physical, partition, max_seq + 1, max_end,
                    )
                    self._create_consuming_segment(
                        physical, partition, max_seq + 1, max_end
                    )


class RealtimeSegmentDataManager:
    """Server-side per-partition consumer
    (LLRealtimeSegmentDataManager.java:68)."""

    def __init__(
        self,
        server,
        manager: RealtimeSegmentManager,
        table: str,
        segment_name: str,
        schema: Schema,
        stream: StreamProvider,
        partition: int,
        start_offset: int,
        rows_per_segment: int,
    ) -> None:
        self.server = server
        self.manager = manager
        self.table = table
        self.segment_name = segment_name
        self.stream = stream
        self.partition = partition
        self.offset = start_offset
        self.rows_per_segment = rows_per_segment
        # cooperative-pool idle cadence (realtime/pool.py): how long a
        # paused/empty/HOLDing consumer stays off its pool worker
        self.poll_interval_s = 0.05
        # rows one pool step may consume (columnar topics serve whole
        # 64k blocks — the ingest ladder raises this to block size so
        # throughput runs aren't bounded by trim-and-refetch)
        self.step_rows = 1000
        self.mutable = MutableSegment(schema, segment_name, table)
        self.mutable.start_offset = start_offset
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        # None = untried; True/False once the stream's columnar support
        # for this partition is known (columnar topics carry whole
        # binary blocks; row-JSON topics raise on fetchc misuse)
        self._columnar: Optional[bool] = None
        # ingest observability: per-partition consumer-lag gauge (latest
        # available stream offset − consumed offset; reads live via
        # set_fn) + rows/s and commit-latency series on the hosting
        # server's registry.  Rolling to the next sequence re-registers
        # the same gauge name, so the series is continuous per
        # (table, partition) across segment commits.
        self._metrics = getattr(server, "metrics", None)
        from pinot_tpu.realtime.stream import LagProbe

        self._lag_probe = LagProbe(stream, partition, lambda: self.offset)
        self._lag_gauge_name = f"ingest.lag.{table}.p{partition}"
        # ingest backpressure: the hosting server's watermark governor
        # (pause above the HBM/mutable high watermark, resume below the
        # low) + a per-consumer paused gauge for per-partition visibility
        self._governor = getattr(server, "ingest_backpressure", None)
        self._paused = False
        self._paused_gauge_name = f"ingest.paused.{table}.p{partition}"
        self._paused_fn = lambda: 1 if self._paused else 0
        # event-time freshness (broker/freshness.py): every indexed
        # batch advances the process-wide (table, partition) watermark
        # to the max of the schema time column; the per-partition lag
        # gauge (now − watermark, ms) re-registers across segment
        # rollover exactly like ingest.lag.* — the series is continuous
        # per (table, partition)
        from pinot_tpu.broker.freshness import WATERMARKS, now_ms

        self._time_col = schema.time_column_name
        self._time_unit_ms = (
            time_unit_to_millis(schema.time_field.time_unit)
            if schema.time_field is not None
            else None
        )
        self._freshness_gauge_name = f"freshness.lag.{table}.p{partition}"

        def _freshness_probe(_t=table, _p=partition):
            w = WATERMARKS.get(_t, _p)
            return round(max(0.0, now_ms() - w), 3) if w is not None else 0
        self._freshness_fn = _freshness_probe
        if self._metrics is not None:
            lag_key = f"{table}.p{partition}"
            self._metrics.gauge(f"ingest.lag.{lag_key}").set_fn(self._lag_probe)
            self._metrics.gauge(f"ingest.paused.{lag_key}").set_fn(self._paused_fn)
            if self._time_col is not None:
                self._metrics.gauge(f"freshness.lag.{lag_key}").set_fn(
                    self._freshness_fn
                )

    def lag(self) -> Optional[int]:
        """Consumer lag in rows: latest available offset on this
        partition minus the consumed offset (0 = fully caught up);
        TTL-cached + failure-degrading (realtime/stream.py LagProbe)."""
        return self._lag_probe()

    def stop(self) -> None:
        self._stopped = True
        # detach the lag gauge: a stopped consumer's frozen offset must
        # not keep reporting (phantom, ever-growing) lag when the
        # partition's successor lands on another server.  The equality
        # guard in clear_fn keeps this safe if a successor on THIS
        # server already re-registered the same series.
        if self._metrics is not None:
            self._metrics.gauge(self._lag_gauge_name).clear_fn(self._lag_probe)
            self._metrics.gauge(self._paused_gauge_name).clear_fn(self._paused_fn)
            self._metrics.gauge(self._freshness_gauge_name).clear_fn(
                self._freshness_fn
            )

    def _mark_rows(self, n: int) -> None:
        if n and self._metrics is not None:
            self._metrics.meter("ingest.rowsConsumed").mark(int(n))

    def _notify_offset_advance(self) -> None:
        """Result-cache watermark hook (engine/rescache.py): the
        consume offset moved, so every cached answer over this table's
        previous watermark is superseded — drop it eagerly.  The
        cache's staging-token key fence already made those entries
        unreachable; this keeps memory and hit-rate honest."""
        cache = getattr(self.server, "result_cache", None)
        if cache is not None and cache.enabled:
            cache.on_offset_advance(self.table, self.partition, self.offset)

    def _advance_watermark(self, time_values) -> None:
        """Event-time watermark advance for one indexed batch
        (broker/freshness.py; monotone — replays can never regress it)."""
        if self._time_unit_ms is None:
            return
        from pinot_tpu.broker.freshness import WATERMARKS, batch_max_event_ms

        event_ms = batch_max_event_ms(time_values, self._time_unit_ms)
        if event_ms is not None:
            WATERMARKS.advance(self.table, self.partition, event_ms)

    # -- consumption ---------------------------------------------------
    def _fetch_and_index(self, limit: int) -> int:
        """One fetch + index against the stream, preferring the
        columnar block path when the provider and partition support it
        (netstream producec topics: np.frombuffer decode + vectorized
        dictionary encode — the 5x ingest path, INGEST_r5.json).
        Returns rows consumed and advances the offset."""
        fetch_cols = getattr(self.stream, "fetch_columns", None)
        if self._columnar is not False and fetch_cols is not None:
            try:
                cols, n, next_offset = fetch_cols(self.partition, self.offset)
            except RuntimeError as e:
                # Only a DEFINITIVE broker verdict may latch row mode:
                # the broker's typed "row-mode partition" rejection, or
                # a broker that doesn't know the fetchc op at all.  A
                # transient transport error must re-raise whether the
                # mode is KNOWN-columnar (the broker rejects row fetches
                # there forever) or still UNKNOWN — latching False on a
                # first-fetch hiccup would wedge ingest on a columnar
                # partition until restart (the consume loop retries the
                # raised error next step instead).
                msg = str(e)
                if "row-mode" in msg or "unknown op" in msg:
                    self._columnar = False  # row-mode partition / no fetchc support
                else:
                    raise
            # any other exception (socket, decode) propagates: never
            # evidence of the partition's mode — always retryable
            else:
                self._columnar = True
                if n <= 0:
                    return 0
                if n > limit:
                    # blocks serve whole; respect the segment budget and
                    # resume MID-block next step (the provider trims)
                    cols = {c: a[:limit] for c, a in cols.items()}
                    next_offset = next_offset - (n - limit)
                    n = limit
                try:
                    self.mutable.index_columns(cols)
                except ValueError:
                    # MV schema / NaN payloads: decode to rows once and
                    # take the row path for this batch
                    names = list(cols)
                    self.mutable.index_batch(
                        [
                            {c: cols[c][i].item() for c in names}
                            for i in range(n)
                        ]
                    )
                self.offset = next_offset
                self.mutable.end_offset = next_offset
                self._mark_rows(n)
                if self._time_col is not None:
                    self._advance_watermark(cols.get(self._time_col))
                self._notify_offset_advance()
                return n
        rows, next_offset = self.stream.fetch(self.partition, self.offset, limit)
        self.mutable.index_batch(rows)
        advanced = next_offset != self.offset
        self.offset = next_offset
        self.mutable.end_offset = next_offset
        self._mark_rows(len(rows))
        if rows and self._time_col is not None:
            self._advance_watermark(
                [r.get(self._time_col) for r in rows if self._time_col in r]
            )
        if advanced:
            self._notify_offset_advance()
        return len(rows)

    def consume_step(self, max_rows: int = 1000) -> int:
        """Fetch + index one (bounded) batch; returns rows consumed.
        Returns 0 WITHOUT touching the stream while the server's ingest
        governor holds consumption above a memory watermark — the offset
        freezes, lag grows visibly, nothing is dropped or skipped."""
        if self._stopped:
            return 0
        if self._governor is not None:
            allowed = self._governor.consume_allowed()
            self._paused = not allowed
            if not allowed:
                return 0
            max_rows = self._governor.clamp_batch(max_rows)
        budget = self.rows_per_segment - self.mutable.num_docs
        if budget <= 0:
            return 0
        return self._fetch_and_index(min(max_rows, budget))

    @property
    def threshold_reached(self) -> bool:
        return self.mutable.num_docs >= self.rows_per_segment

    def step(self) -> Optional[float]:
        """One cooperative pool unit (realtime/pool.py): a bounded
        consume batch, plus one completion-protocol round at the row
        threshold.  Returns seconds until this consumer is eligible
        again, or None when finished (committed/discarded/stopped —
        the successor sequence gets its own consumer).  Never blocks:
        a backpressure pause, an empty stream, or a completion HOLD
        all surface as an idle delay so the shared workers stay free
        for the other partitions."""
        if self._stopped:
            return None
        got = self.consume_step(self.step_rows)
        if self.threshold_reached:
            resp = self.try_commit()
            if self._stopped or resp in (RESP_KEEP, RESP_DISCARD):
                # on_segment_committed retires this consumer (stop());
                # KEEP/DISCARD mean the sequence is settled elsewhere
                return None
            # HOLD / CATCH_UP / NOT_LEADER / lease-frozen: retry later
            return self.poll_interval_s
        if self._paused or got == 0:
            return self.poll_interval_s
        return 0.0

    def try_commit(self) -> str:
        """Run the completion protocol once
        (segmentConsumed -> maybe segmentCommit).  A server whose
        serving lease expired has no write authority: the round is
        frozen (HOLD) — offsets keep, nothing is lost — until the
        lease renews."""
        if self._stopped:
            return RESP_DISCARD
        lease = getattr(self.server, "lease", None)
        epoch = None
        if lease is not None:
            if not lease.held():
                if self._metrics is not None:
                    self._metrics.meter("lease.blockedCommits").mark()
                return RESP_HOLD
            if lease.granted:
                epoch = lease.epoch
        completion = self.manager.completion
        resp, target = completion.segment_consumed(
            self.segment_name, self.server.name, self.offset, epoch=epoch
        )
        if resp == RESP_CATCH_UP and target is not None:
            while self.offset < target and not self._stopped:
                if self._fetch_and_index(target - self.offset) == 0:
                    break
            return resp
        if resp == RESP_COMMIT:
            t0 = time.perf_counter()
            committed = self.mutable.to_committed_segment()
            out = completion.segment_commit(
                self.segment_name, self.server.name, committed, epoch=epoch
            )
            # commit latency: mutable->immutable conversion + the
            # controller persistence round (the ingest stall window)
            if self._metrics is not None:
                self._metrics.timer("ingest.commitMs").update(
                    (time.perf_counter() - t0) * 1000
                )
            return out
        return resp
