"""Network stream broker: a TCP pub/sub log for realtime ingestion.

The reference's realtime story is a network consumer per partition
pulling from Kafka by exact offset (``SimpleConsumerWrapper.java``,
``LLRealtimeSegmentDataManager.java:68``).  No Kafka ships in this
image, so this module provides the same capability natively: a
stream-broker *process* holding topic/partition append-only logs,
addressed by offset over the same 4-byte-length-framed TCP transport
the query data plane uses (``transport/tcp.py``), plus a
``NetworkStreamProvider`` client speaking the offset-addressed
``StreamProvider`` interface that the LLC machinery consumes.

Protocol: one JSON object per frame.
  {"op": "create",  "topic": t, "partitions": n}
  {"op": "produce", "topic": t, "partition": p, "rows": [{...}, ...]}
      -> {"firstOffset": o, "nextOffset": o'}
  {"op": "fetch",   "topic": t, "partition": p, "offset": o, "maxRows": m}
      -> {"rows": [...], "nextOffset": o'}
  {"op": "latest",  "topic": t, "partition": p} -> {"offset": o}
  {"op": "meta",    "topic": t} -> {"partitions": n}

Consumer groups (the HLC analog — broker-coordinated membership,
partition rebalance, durable group offsets; see ``HLConsumer``):
  {"op": "join",      "topic": t, "group": g, "consumer": c}
      -> {"generation": n, "assignment": [p...], "members": [...], "offsets": {...}}
  {"op": "heartbeat", "topic": t, "group": g, "consumer": c, "generation": n}
      -> {"status": "ok"} | {"rebalance": true, "generation": n'}
  {"op": "commit",    "topic": t, "group": g, "generation": n, "offsets": {p: o}}
  {"op": "committed", "topic": t, "group": g} -> {"offsets": {p: o}}
  {"op": "leave",     "topic": t, "group": g, "consumer": c}

Durability: with ``log_dir`` set, every partition is an append-only
JSONL log replayed on broker restart — consumers resume at their
committed offsets across broker crashes, like Kafka's on-disk log.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import logging

from pinot_tpu.realtime.stream import StreamProvider
from pinot_tpu.transport.tcp import TcpServer, TcpTransport
from pinot_tpu.utils.fileio import atomic_write

logger = logging.getLogger(__name__)


Row = Dict[str, Any]


class _RowView:
    """Decode-on-access view of one partition's raw serialized log —
    keeps dict-shaped access (kafka bridge, tests) over the byte-level
    store without materializing decoded rows broker-side."""

    def __init__(self, raw: List[bytes]) -> None:
        self._raw = raw

    def __len__(self) -> int:
        return len(self._raw)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [json.loads(b) for b in self._raw[i]]
        return json.loads(self._raw[i])

    def __iter__(self):
        return (json.loads(b) for b in self._raw)


class _Topic:
    """Partition logs stored as SERIALIZED per-row JSON bytes: rows are
    encoded once at produce; a fetch response is a byte splice with no
    re-serialization.  (The r4 store kept decoded dicts and re-dumped
    them on every fetch — at 4 concurrent consumers the broker's GIL
    became the whole pipeline's ceiling.)"""

    def __init__(self, partitions: int, log_paths: Optional[List[str]] = None) -> None:
        self.raw: List[List[bytes]] = [[] for _ in range(partitions)]
        self.columnar: Optional["_ColumnarLog"] = None  # created on first producec
        self.log_paths = log_paths
        self._log_files = None
        if log_paths is not None:
            for p, path in enumerate(log_paths):
                if os.path.exists(path):
                    self.raw[p] = self._recover(path)
            self._log_files = [open(path, "ab") for path in log_paths]

    def count(self, partition: int) -> int:
        if self.columnar is not None and self.columnar.counts[partition]:
            return self.columnar.counts[partition]
        return len(self.raw[partition])

    @property
    def rows(self) -> List[_RowView]:
        return [_RowView(r) for r in self.raw]

    @staticmethod
    def _recover(path: str) -> List[bytes]:
        """Replay a partition log, truncating a torn tail: a crash
        (SIGKILL mid-append) can leave a partial last line, which must
        not stop the broker from coming back up (Kafka log recovery
        semantics).  Only a torn FINAL line is dropped; corruption
        earlier in the log still raises."""
        raw: List[bytes] = []
        lines = open(path, "rb").read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                json.loads(line)
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    # drop the torn tail atomically: a crash *during
                    # recovery* must not lose the whole log
                    atomic_write(
                        path, b"".join(l + b"\n" for l in lines[:i]), binary=True
                    )
                    break
                raise
            else:
                raw.append(bytes(line))
        return raw

    def append(self, partition: int, rows: Sequence[Row]) -> int:
        first = len(self.raw[partition])
        encoded = [
            json.dumps(row, separators=(",", ":")).encode("utf-8") for row in rows
        ]
        self.raw[partition].extend(encoded)
        if self._log_files is not None:
            f = self._log_files[partition]
            f.write(b"".join(b + b"\n" for b in encoded))
            f.flush()
        return first

    def fetch_frame(self, partition: int, offset: int, max_rows: int) -> bytes:
        """One fetch reply frame spliced from stored bytes."""
        chunk = self.raw[partition][offset : offset + max_rows]
        return (
            b'{"rows":[' + b",".join(chunk) + b'],"nextOffset":'
            + str(offset + len(chunk)).encode() + b"}"
        )

    def close(self) -> None:
        if self._log_files is not None:
            for f in self._log_files:
                f.close()


COLUMNAR_MAGIC = b"\xffC"  # cannot open a JSON frame


def pack_columnar(header: Dict[str, Any], buffers: Sequence[bytes]) -> bytes:
    hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
    import struct

    return COLUMNAR_MAGIC + struct.pack("<I", len(hj)) + hj + b"".join(buffers)


def unpack_columnar(frame: bytes):
    """-> (header dict, buffer bytes after the header)."""
    import struct

    (hlen,) = struct.unpack_from("<I", frame, 2)
    header = json.loads(frame[6 : 6 + hlen].decode("utf-8"))
    return header, frame[6 + hlen :]


class _ColumnarLog:
    """Columnar block log for one topic: whole produce blocks stored
    verbatim (start-offset keyed), served back as fetch frames with no
    re-encoding — the high-throughput ingest transport (row-JSON costs
    ~1.3us/row just to decode; a columnar block decodes with
    np.frombuffer).  A partition is row-mode or columnar-mode from its
    first produce; mixing is an error."""

    def __init__(self, partitions: int) -> None:
        # per partition: list of (start, n, cols_spec, buffers bytes)
        self.blocks: List[List[tuple]] = [[] for _ in range(partitions)]
        self.counts: List[int] = [0] * partitions

    def append(self, partition: int, n: int, cols_spec, buffers: bytes) -> int:
        first = self.counts[partition]
        self.blocks[partition].append((first, n, cols_spec, buffers))
        self.counts[partition] = first + n
        return first

    def fetch_frame(self, partition: int, offset: int) -> bytes:
        # blocks are consumed whole: a consumer always passes back the
        # nextOffset the previous reply carried, so binary-search the
        # block whose start covers the requested offset
        blocks = self.blocks[partition]
        lo, hi = 0, len(blocks)
        while lo < hi:
            mid = (lo + hi) // 2
            if blocks[mid][0] + blocks[mid][1] <= offset:
                lo = mid + 1
            else:
                hi = mid
        if lo >= len(blocks):
            return pack_columnar(
                {"n": 0, "start": offset, "nextOffset": offset, "cols": []}, []
            )
        start, n, cols_spec, buffers = blocks[lo]
        return pack_columnar(
            {"n": n, "start": start, "nextOffset": start + n, "cols": cols_spec},
            [buffers],
        )


class _Group:
    """Consumer-group state for one (group, topic): membership with
    heartbeat expiry, a generation counter bumped on every rebalance,
    and per-partition committed offsets — the broker-side analog of the
    reference HLC's ZK-committed consumer-group state
    (``KafkaHighLevelConsumerStreamProvider.java``)."""

    def __init__(self) -> None:
        self.members: Dict[str, float] = {}  # consumer id -> last heartbeat
        self.generation = 0
        self.offsets: Dict[int, int] = {}
        self.session_timeout = 30.0
        self.partitions_seen = -1  # topic width at last (re)balance
        self.acked: Dict[str, int] = {}  # consumer -> last generation it joined

    def sync_pending(self) -> bool:
        """True until every live member has (re)joined the current
        generation — the rebalance sync barrier: members revoke-commit
        before rejoining, so once sync completes the committed offsets
        cover everything consumed under older generations and new
        owners cannot replay another member's uncommitted rows."""
        return any(self.acked.get(m, -1) != self.generation for m in self.members)

    def expire(self, now: float) -> bool:
        dead = [c for c, t in self.members.items() if now - t > self.session_timeout]
        for c in dead:
            del self.members[c]
            self.acked.pop(c, None)
        if dead:
            self.generation += 1
        return bool(dead)

    def assignment(self, consumer: str, partitions: int) -> List[int]:
        order = sorted(self.members)
        if consumer not in order:
            return []
        i = order.index(consumer)
        return list(range(partitions))[i :: len(order)]


class StreamBrokerServer:
    """The broker process: topics of offset-addressed partition logs."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        log_dir: Optional[str] = None,
    ) -> None:
        self.log_dir = log_dir
        self._topics: Dict[str, _Topic] = {}
        self._groups: Dict[Tuple[str, str], _Group] = {}  # (group, topic)
        self._lock = threading.Lock()
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            self._load_groups()
            # recover topics from on-disk logs
            for name in sorted(os.listdir(log_dir)):
                tdir = os.path.join(log_dir, name)
                if not os.path.isdir(tdir):
                    continue
                # order by numeric partition index: lexicographic sort
                # would put p10 before p2 and scramble the mapping
                indexed = []
                for f in os.listdir(tdir):
                    if f.startswith("p") and f.endswith(".jsonl"):
                        try:
                            indexed.append((int(f[1 : -len(".jsonl")]), f))
                        except ValueError:
                            continue
                paths = [
                    os.path.join(tdir, f) for _, f in sorted(indexed)
                ]
                if paths:
                    self._topics[name] = _Topic(len(paths), paths)
        self.server = TcpServer(self._handle, host=host, port=port)

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()
        with self._lock:
            for t in self._topics.values():
                t.close()

    # -- topic management (also usable in-process) ---------------------
    def create_topic(self, topic: str, partitions: int) -> None:
        with self._lock:
            if topic in self._topics:
                return
            log_paths = None
            if self.log_dir is not None:
                tdir = os.path.join(self.log_dir, topic)
                os.makedirs(tdir, exist_ok=True)
                log_paths = [
                    os.path.join(tdir, f"p{p}.jsonl") for p in range(partitions)
                ]
            self._topics[topic] = _Topic(partitions, log_paths)

    # -- consumer-group offset durability ------------------------------
    def _groups_path(self) -> Optional[str]:
        if self.log_dir is None:
            return None
        return os.path.join(self.log_dir, "__groups__.json")

    def _load_groups(self) -> None:
        path = self._groups_path()
        if path is None or not os.path.exists(path):
            return
        for key, offs in json.load(open(path)).items():
            group, topic = key.split("\x00", 1)
            g = _Group()
            g.offsets = {int(p): int(o) for p, o in offs.items()}
            self._groups[(group, topic)] = g

    def _save_groups(self) -> None:
        path = self._groups_path()
        if path is None:
            return
        data = {
            f"{group}\x00{topic}": g.offsets
            for (group, topic), g in self._groups.items()
        }
        atomic_write(path, json.dumps(data))

    def _group_op(self, op: str, req: Dict[str, Any]) -> bytes:
        """join / heartbeat / leave / commit / committed — must be
        called with the lock held."""
        import time as _time

        key = (req["group"], req["topic"])
        g = self._groups.setdefault(key, _Group())
        now = _time.monotonic()
        consumer = req.get("consumer", "")
        topic = self._topics.get(req["topic"])
        partitions = len(topic.rows) if topic is not None else 0
        if op == "join":
            g.expire(now)
            g.session_timeout = float(req.get("sessionTimeout", g.session_timeout))
            if consumer not in g.members or partitions != g.partitions_seen:
                g.generation += 1
            g.partitions_seen = partitions
            g.members[consumer] = now
            g.acked[consumer] = g.generation
            assignment = g.assignment(consumer, partitions)
            pending = g.sync_pending()
            logger.info(
                "group %s: %s joined gen=%d assignment=%s members=%s "
                "syncPending=%s offsets=%s",
                key, consumer, g.generation, assignment, sorted(g.members),
                pending, g.offsets,
            )
            return json.dumps(
                {
                    "generation": g.generation,
                    "assignment": assignment,
                    "members": sorted(g.members),
                    "offsets": g.offsets,
                    "syncPending": pending,
                }
            ).encode()
        if op == "heartbeat":
            changed = g.expire(now)
            if consumer in g.members:
                g.members[consumer] = now
                if int(req.get("generation", -1)) == g.generation:
                    g.acked[consumer] = g.generation
            if partitions != g.partitions_seen:
                # topic created or widened since the last (re)balance:
                # force every member through a rejoin so assignments
                # cover the new partitions
                g.generation += 1
                g.partitions_seen = partitions
                changed = True
            if changed or int(req.get("generation", -1)) != g.generation:
                return json.dumps({"rebalance": True, "generation": g.generation}).encode()
            return json.dumps({"status": "ok", "generation": g.generation}).encode()
        if op == "leave":
            if consumer in g.members:
                del g.members[consumer]
                g.generation += 1
            return json.dumps({"status": "ok"}).encode()
        if op == "commit":
            if consumer not in g.members:
                # a departed/expired consumer must not write offsets
                return json.dumps({"rebalance": True, "generation": g.generation}).encode()
            # monotonic, generation-independent: a live member commits
            # positions for partitions it is LOSING during a rebalance
            # (the revoke-commit) so the next owner resumes where it
            # stopped instead of replaying — offsets only move forward
            for p, off in req.get("offsets", {}).items():
                pi = int(p)
                g.offsets[pi] = max(int(g.offsets.get(pi, 0)), int(off))
            self._save_groups()
            return json.dumps({"status": "ok"}).encode()
        if op == "committed":
            return json.dumps({"offsets": g.offsets}).encode()
        if op == "describe":
            return json.dumps(
                {
                    "members": sorted(g.members),
                    "generation": g.generation,
                    "syncPending": g.sync_pending(),
                }
            ).encode()
        return json.dumps({"error": f"unknown group op {op!r}"}).encode()

    def _handle(self, payload: bytes) -> bytes:
        if payload[:2] == COLUMNAR_MAGIC:
            return self._handle_columnar(payload)
        req = json.loads(payload.decode("utf-8"))
        op = req.get("op")
        try:
            if op == "create":
                self.create_topic(req["topic"], int(req.get("partitions", 1)))
                return json.dumps({"status": "ok"}).encode()
            if op in ("join", "heartbeat", "leave", "commit", "committed", "describe"):
                with self._lock:
                    return self._group_op(op, req)
            with self._lock:
                topic = self._topics.get(req.get("topic", ""))
                if topic is None:
                    return json.dumps({"error": "unknown topic"}).encode()
                if op == "produce":
                    p = int(req.get("partition", 0))
                    if topic.columnar is not None and topic.columnar.counts[p]:
                        return json.dumps(
                            {"error": "partition already columnar-mode"}
                        ).encode()
                    first = topic.append(p, req.get("rows", []))
                    return json.dumps(
                        {"firstOffset": first, "nextOffset": len(topic.raw[p])}
                    ).encode()
                if op == "fetch":
                    p = int(req.get("partition", 0))
                    off = int(req.get("offset", 0))
                    m = int(req.get("maxRows", 1000))
                    if topic.columnar is not None and topic.columnar.counts[p]:
                        return json.dumps({"error": "columnar partition"}).encode()
                    return topic.fetch_frame(p, off, m)
                if op == "fetchc":
                    p = int(req.get("partition", 0))
                    off = int(req.get("offset", 0))
                    if len(topic.raw[p]):
                        return json.dumps({"error": "row-mode partition"}).encode()
                    columnar = topic.columnar
                    if columnar is None:
                        return pack_columnar(
                            {"n": 0, "start": off, "nextOffset": off, "cols": []},
                            [],
                        )
                elif op == "latest":
                    p = int(req.get("partition", 0))
                    return json.dumps({"offset": topic.count(p)}).encode()
                elif op == "meta":
                    return json.dumps({"partitions": len(topic.raw)}).encode()
                else:
                    return json.dumps({"error": f"unknown op {op!r}"}).encode()
            # fetchc reaches here: splice the reply OUTSIDE the broker
            # lock — packing a multi-megabyte block frame under it
            # would serialize every partition-parallel consumer on one
            # fetch (the block list is append-only, so a concurrent
            # produce is at worst not-yet-visible, never torn)
            return columnar.fetch_frame(p, off)
        except (KeyError, IndexError, ValueError) as e:
            return json.dumps({"error": str(e)}).encode()
        except Exception as e:  # never kill the connection on a bad frame
            logger.warning("stream broker op %r failed", op, exc_info=True)
            return json.dumps({"error": f"{type(e).__name__}: {e}"}).encode()

    def _handle_columnar(self, payload: bytes) -> bytes:
        """Binary columnar produce: the block is stored VERBATIM and
        served back by fetchc with zero broker-side (de)serialization."""
        try:
            header, buffers = unpack_columnar(payload)
            if header.get("op") != "producec":
                return json.dumps({"error": "bad columnar op"}).encode()
            p = int(header.get("partition", 0))
            import numpy as _np

            expect = sum(
                int(header["n"]) * _np.dtype(dt).itemsize
                for _, dt in header["cols"]
            )
            if expect != len(buffers):
                return json.dumps(
                    {"error": f"columnar buffer size mismatch: {len(buffers)} != {expect}"}
                ).encode()
            with self._lock:
                topic = self._topics.get(header.get("topic", ""))
                if topic is None:
                    return json.dumps({"error": "unknown topic"}).encode()
                if topic.log_paths is not None:
                    return json.dumps(
                        {"error": "columnar produce unsupported on durable-log topics"}
                    ).encode()
                if len(topic.raw[p]):
                    return json.dumps(
                        {"error": "partition already row-mode"}
                    ).encode()
                if topic.columnar is None:
                    topic.columnar = _ColumnarLog(len(topic.raw))
                first = topic.columnar.append(
                    p, int(header["n"]), header["cols"], buffers
                )
                return json.dumps(
                    {"firstOffset": first, "nextOffset": topic.columnar.counts[p]}
                ).encode()
        except Exception as e:
            logger.warning("columnar produce failed", exc_info=True)
            return json.dumps({"error": f"{type(e).__name__}: {e}"}).encode()


class NetworkStreamProvider(StreamProvider):
    """LLC-shaped consumer client of a StreamBrokerServer — the
    SimpleConsumerWrapper analog (exact-offset fetch over TCP)."""

    def __init__(self, host: str, port: int, topic: str) -> None:
        self.host = host
        self.port = int(port)
        self.topic = topic
        self._transport = TcpTransport()

    _IDEMPOTENT_OPS = ("create", "fetch", "latest", "meta",
                       "join", "heartbeat", "leave", "commit", "committed", "describe")

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        payload = json.dumps({"topic": self.topic, **req}).encode()
        try:
            raw = self._transport.request((self.host, self.port), payload)
        except Exception:
            # connection resets happen under fd/process churn; all ops
            # except produce are idempotent (group commits are
            # monotonic), so one retry on a fresh connection is safe
            if req.get("op") not in self._IDEMPOTENT_OPS:
                raise
            time.sleep(0.05)
            raw = self._transport.request((self.host, self.port), payload)
        reply = json.loads(raw.decode("utf-8"))
        if "error" in reply:
            raise RuntimeError(f"stream broker: {reply['error']}")
        return reply

    def describe(self) -> Dict[str, Any]:
        """Descriptor for the controller property store, so recovered
        controllers (and remote consumers) can reconnect."""
        return {
            "type": "network",
            "host": self.host,
            "port": self.port,
            "topic": self.topic,
        }

    def partition_count(self) -> int:
        return int(self._call({"op": "meta"})["partitions"])

    def fetch(self, partition: int, offset: int, max_rows: int) -> Tuple[List[Row], int]:
        out = self._call(
            {"op": "fetch", "partition": partition, "offset": offset, "maxRows": max_rows}
        )
        return out["rows"], int(out["nextOffset"])

    def latest_offset(self, partition: int) -> int:
        return int(self._call({"op": "latest", "partition": partition})["offset"])

    def produce(self, row: Row, partition: int = 0) -> int:
        """Producer convenience (tests/quickstarts)."""
        return int(
            self._call({"op": "produce", "partition": partition, "rows": [row]})[
                "firstOffset"
            ]
        )

    def produce_batch(self, rows: Sequence[Row], partition: int = 0) -> int:
        return int(
            self._call({"op": "produce", "partition": partition, "rows": list(rows)})[
                "firstOffset"
            ]
        )

    def create_topic(self, partitions: int) -> None:
        self._call({"op": "create", "partitions": partitions})

    # -- columnar fast path -------------------------------------------
    def produce_columns(self, cols: Dict[str, Any], partition: int = 0) -> int:
        """Produce one columnar block (dict of equal-length numpy
        arrays).  Stored verbatim broker-side; the matching consumer
        call is :meth:`fetch_columns`."""
        import numpy as np

        names = list(cols)
        arrays = [np.ascontiguousarray(cols[c]) for c in names]
        n = len(arrays[0]) if arrays else 0
        if any(len(a) != n for a in arrays):
            raise ValueError("columnar block arrays must share one length")
        header = {
            "op": "producec",
            "topic": self.topic,
            "partition": partition,
            "n": n,
            "cols": [[c, a.dtype.str] for c, a in zip(names, arrays)],
        }
        frame = pack_columnar(header, [a.tobytes() for a in arrays])
        raw = self._transport.request((self.host, self.port), frame)
        reply = json.loads(raw.decode("utf-8"))
        if "error" in reply:
            raise RuntimeError(f"stream broker: {reply['error']}")
        return int(reply["firstOffset"])

    def fetch_columns(self, partition: int, offset: int):
        """-> (cols dict of numpy arrays, n, nextOffset): one whole
        produced block, decoded with np.frombuffer (no row objects)."""
        import numpy as np

        payload = json.dumps(
            {"op": "fetchc", "topic": self.topic, "partition": partition, "offset": offset}
        ).encode()
        raw = self._transport.request((self.host, self.port), payload)
        if raw[:2] != COLUMNAR_MAGIC:
            reply = json.loads(raw.decode("utf-8"))
            raise RuntimeError(f"stream broker: {reply.get('error', 'bad reply')}")
        header, buffers = unpack_columnar(raw)
        n = int(header["n"])
        out: Dict[str, Any] = {}
        pos = 0
        for name, dt in header["cols"]:
            dtype = np.dtype(dt)
            size = n * dtype.itemsize
            out[name] = np.frombuffer(buffers[pos : pos + size], dtype=dtype)
            pos += size
        # blocks serve whole: a resume offset landing MID-block trims
        # the rows before it so no consumer ever re-ingests duplicates
        start = int(header.get("start", offset))
        if n and start < offset:
            skip = offset - start
            out = {c: a[skip:] for c, a in out.items()}
            n -= skip
        return out, n, int(header["nextOffset"])


class HLConsumer:
    """High-level consumer-group member — the HLC analog
    (``HLRealtimeSegmentDataManager.java:54``,
    ``KafkaHighLevelConsumerStreamProvider.java``): the broker assigns
    partitions across the group's live members, rebalances on
    join/leave/expiry, and stores group-committed offsets durably; the
    consumer just polls its current assignment and commits.
    """

    def __init__(
        self,
        host: str,
        port: int,
        topic: str,
        group: str,
        consumer_id: str,
        session_timeout: float = 30.0,
    ) -> None:
        self.provider = NetworkStreamProvider(host, port, topic)
        self.topic = topic
        self.group = group
        self.consumer_id = consumer_id
        self.session_timeout = session_timeout
        # called when a rebalance revokes this member's assignment,
        # BEFORE rejoining: persist consumed-but-uncommitted work (seal
        # + commit) or discard it — returning normally means the member
        # is clean and successors may take over its partitions
        self.on_revoke = None
        self.generation = -1
        self.assignment: List[int] = []
        self.positions: Dict[int, int] = {}
        self.sync_pending = False

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        payload = {"group": self.group, "consumer": self.consumer_id, **req}
        try:
            return self.provider._call(payload)
        except Exception:
            # group ops are idempotent (commits are monotonic): one
            # retry rides out a connection reset under load
            time.sleep(0.05)
            return self.provider._call(payload)

    def join(self) -> List[int]:
        out = self._call({"op": "join", "sessionTimeout": self.session_timeout})
        self.generation = int(out["generation"])
        self.assignment = [int(p) for p in out["assignment"]]
        committed = {int(p): int(o) for p, o in out.get("offsets", {}).items()}
        # positions restart from the group's committed offsets; a
        # partition this member kept across the rebalance resumes from
        # its own (possibly further) position — those rows are already
        # in its local segment, re-reading them would duplicate
        self.positions = {
            p: max(committed.get(p, 0), self.positions.get(p, 0))
            for p in self.assignment
        }
        self.sync_pending = bool(out.get("syncPending"))
        return self.assignment

    def poll(self, max_rows_per_partition: int = 500) -> List[Tuple[int, Row]]:
        """Heartbeat, rejoin if the group rebalanced, then drain up to
        ``max_rows_per_partition`` from each assigned partition.
        Returns (partition, row) pairs."""
        hb = self._call({"op": "heartbeat", "generation": self.generation})
        if hb.get("rebalance"):
            # revoke: make consumed work durable (or drop it) before
            # the new assignment, so successors neither replay rows a
            # live member still serves nor skip rows nobody persisted
            try:
                if self.on_revoke is not None:
                    self.on_revoke()
                else:
                    self.commit()
            except Exception:
                # The hook owns persist-or-discard of locally consumed
                # rows and handles its own failures (seal/upload errors
                # discard + reset internally); it raising means local
                # state is unknown.  Keep positions as-is — join() floors
                # them at committed, and guessing here (e.g. resetting)
                # would re-fetch rows whose seal already made them
                # durable.  Surface the bug loudly instead of silently
                # continuing (ADVICE r2).
                logger.exception(
                    "on_revoke failed for %s/%s", self.group, self.consumer_id
                )
            self.join()
        if self.sync_pending:
            # rebalance sync barrier: hold fetches until every member
            # has revoke-committed + rejoined the current generation
            self.join()
            if self.sync_pending:
                return []
        out: List[Tuple[int, Row]] = []
        for p in self.assignment:
            rows, nxt = self.provider.fetch(
                p, self.positions.get(p, 0), max_rows_per_partition
            )
            out.extend((p, r) for r in rows)
            self.positions[p] = nxt
        return out

    def commit(self) -> bool:
        """Commit current positions; False if a rebalance intervened
        (caller rejoins on next poll and replays from committed)."""
        out = self._call(
            {
                "op": "commit",
                "generation": self.generation,
                "offsets": {str(p): self.positions[p] for p in self.assignment},
            }
        )
        return not out.get("rebalance", False)

    def committed_offsets(self) -> Dict[int, int]:
        out = self._call({"op": "committed"})
        return {int(p): int(o) for p, o in out["offsets"].items()}

    def reset_to_committed(self) -> None:
        """Drop local positions back to the group's committed offsets —
        required after discarding locally-consumed-but-unpersisted rows
        (they must be re-fetched, not skipped)."""
        committed = self.committed_offsets()
        self.positions = {p: committed.get(p, 0) for p in self.assignment}

    def describe_group(self) -> Dict[str, Any]:
        """Group membership/state without joining (ops tooling + tests)."""
        return self._call({"op": "describe"})

    def close(self) -> None:
        try:
            self._call({"op": "leave"})
        except Exception:
            pass
