"""Network stream broker: a TCP pub/sub log for realtime ingestion.

The reference's realtime story is a network consumer per partition
pulling from Kafka by exact offset (``SimpleConsumerWrapper.java``,
``LLRealtimeSegmentDataManager.java:68``).  No Kafka ships in this
image, so this module provides the same capability natively: a
stream-broker *process* holding topic/partition append-only logs,
addressed by offset over the same 4-byte-length-framed TCP transport
the query data plane uses (``transport/tcp.py``), plus a
``NetworkStreamProvider`` client speaking the offset-addressed
``StreamProvider`` interface that the LLC machinery consumes.

Protocol: one JSON object per frame.
  {"op": "create",  "topic": t, "partitions": n}
  {"op": "produce", "topic": t, "partition": p, "rows": [{...}, ...]}
      -> {"firstOffset": o, "nextOffset": o'}
  {"op": "fetch",   "topic": t, "partition": p, "offset": o, "maxRows": m}
      -> {"rows": [...], "nextOffset": o'}
  {"op": "latest",  "topic": t, "partition": p} -> {"offset": o}
  {"op": "meta",    "topic": t} -> {"partitions": n}

Durability: with ``log_dir`` set, every partition is an append-only
JSONL log replayed on broker restart — consumers resume at their
committed offsets across broker crashes, like Kafka's on-disk log.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pinot_tpu.realtime.stream import StreamProvider
from pinot_tpu.transport.tcp import TcpServer, TcpTransport

Row = Dict[str, Any]


class _Topic:
    def __init__(self, partitions: int, log_paths: Optional[List[str]] = None) -> None:
        self.rows: List[List[Row]] = [[] for _ in range(partitions)]
        self.log_paths = log_paths
        self._log_files = None
        if log_paths is not None:
            for p, path in enumerate(log_paths):
                if os.path.exists(path):
                    self.rows[p] = self._recover(path)
            self._log_files = [open(path, "a") for path in log_paths]

    @staticmethod
    def _recover(path: str) -> List[Row]:
        """Replay a partition log, truncating a torn tail: a crash
        (SIGKILL mid-append) can leave a partial last line, which must
        not stop the broker from coming back up (Kafka log recovery
        semantics).  Only a torn FINAL line is dropped; corruption
        earlier in the log still raises."""
        rows: List[Row] = []
        lines = open(path).read().splitlines()
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                if i == len(lines) - 1:
                    with open(path, "w") as f:
                        f.write("".join(l + "\n" for l in lines[:i]))
                    break
                raise
        return rows

    def append(self, partition: int, rows: Sequence[Row]) -> int:
        first = len(self.rows[partition])
        self.rows[partition].extend(rows)
        if self._log_files is not None:
            f = self._log_files[partition]
            for row in rows:
                f.write(json.dumps(row) + "\n")
            f.flush()
        return first

    def close(self) -> None:
        if self._log_files is not None:
            for f in self._log_files:
                f.close()


class StreamBrokerServer:
    """The broker process: topics of offset-addressed partition logs."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        log_dir: Optional[str] = None,
    ) -> None:
        self.log_dir = log_dir
        self._topics: Dict[str, _Topic] = {}
        self._lock = threading.Lock()
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            # recover topics from on-disk logs
            for name in sorted(os.listdir(log_dir)):
                tdir = os.path.join(log_dir, name)
                if not os.path.isdir(tdir):
                    continue
                # order by numeric partition index: lexicographic sort
                # would put p10 before p2 and scramble the mapping
                indexed = []
                for f in os.listdir(tdir):
                    if f.startswith("p") and f.endswith(".jsonl"):
                        try:
                            indexed.append((int(f[1 : -len(".jsonl")]), f))
                        except ValueError:
                            continue
                paths = [
                    os.path.join(tdir, f) for _, f in sorted(indexed)
                ]
                if paths:
                    self._topics[name] = _Topic(len(paths), paths)
        self.server = TcpServer(self._handle, host=host, port=port)

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> None:
        self.server.start()

    def stop(self) -> None:
        self.server.stop()
        with self._lock:
            for t in self._topics.values():
                t.close()

    # -- topic management (also usable in-process) ---------------------
    def create_topic(self, topic: str, partitions: int) -> None:
        with self._lock:
            if topic in self._topics:
                return
            log_paths = None
            if self.log_dir is not None:
                tdir = os.path.join(self.log_dir, topic)
                os.makedirs(tdir, exist_ok=True)
                log_paths = [
                    os.path.join(tdir, f"p{p}.jsonl") for p in range(partitions)
                ]
            self._topics[topic] = _Topic(partitions, log_paths)

    def _handle(self, payload: bytes) -> bytes:
        req = json.loads(payload.decode("utf-8"))
        op = req.get("op")
        try:
            if op == "create":
                self.create_topic(req["topic"], int(req.get("partitions", 1)))
                return json.dumps({"status": "ok"}).encode()
            with self._lock:
                topic = self._topics.get(req.get("topic", ""))
                if topic is None:
                    return json.dumps({"error": "unknown topic"}).encode()
                if op == "produce":
                    p = int(req.get("partition", 0))
                    first = topic.append(p, req.get("rows", []))
                    return json.dumps(
                        {"firstOffset": first, "nextOffset": len(topic.rows[p])}
                    ).encode()
                if op == "fetch":
                    p = int(req.get("partition", 0))
                    off = int(req.get("offset", 0))
                    m = int(req.get("maxRows", 1000))
                    rows = topic.rows[p][off : off + m]
                    return json.dumps(
                        {"rows": rows, "nextOffset": off + len(rows)}
                    ).encode()
                if op == "latest":
                    p = int(req.get("partition", 0))
                    return json.dumps({"offset": len(topic.rows[p])}).encode()
                if op == "meta":
                    return json.dumps({"partitions": len(topic.rows)}).encode()
            return json.dumps({"error": f"unknown op {op!r}"}).encode()
        except (KeyError, IndexError, ValueError) as e:
            return json.dumps({"error": str(e)}).encode()


class NetworkStreamProvider(StreamProvider):
    """LLC-shaped consumer client of a StreamBrokerServer — the
    SimpleConsumerWrapper analog (exact-offset fetch over TCP)."""

    def __init__(self, host: str, port: int, topic: str) -> None:
        self.host = host
        self.port = int(port)
        self.topic = topic
        self._transport = TcpTransport()

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        payload = json.dumps({"topic": self.topic, **req}).encode()
        reply = json.loads(
            self._transport.request((self.host, self.port), payload).decode("utf-8")
        )
        if "error" in reply:
            raise RuntimeError(f"stream broker: {reply['error']}")
        return reply

    def describe(self) -> Dict[str, Any]:
        """Descriptor for the controller property store, so recovered
        controllers (and remote consumers) can reconnect."""
        return {
            "type": "network",
            "host": self.host,
            "port": self.port,
            "topic": self.topic,
        }

    def partition_count(self) -> int:
        return int(self._call({"op": "meta"})["partitions"])

    def fetch(self, partition: int, offset: int, max_rows: int) -> Tuple[List[Row], int]:
        out = self._call(
            {"op": "fetch", "partition": partition, "offset": offset, "maxRows": max_rows}
        )
        return out["rows"], int(out["nextOffset"])

    def latest_offset(self, partition: int) -> int:
        return int(self._call({"op": "latest", "partition": partition})["offset"])

    def produce(self, row: Row, partition: int = 0) -> int:
        """Producer convenience (tests/quickstarts)."""
        return int(
            self._call({"op": "produce", "partition": partition, "rows": [row]})[
                "firstOffset"
            ]
        )

    def produce_batch(self, rows: Sequence[Row], partition: int = 0) -> int:
        return int(
            self._call({"op": "produce", "partition": partition, "rows": list(rows)})[
                "firstOffset"
            ]
        )

    def create_topic(self, partitions: int) -> None:
        self._call({"op": "create", "partitions": partitions})
