"""Stream providers: offset-addressed row sources for realtime ingestion.

The reference consumes Kafka two ways — high-level consumer groups
(HLC, ``KafkaHighLevelConsumerStreamProvider``) and low-level
per-partition simple consumers with exact offsets (LLC,
``SimpleConsumerWrapper.java``) — and ships a file-backed fake for
tests (``FileBasedStreamProviderImpl.java``).

Here every provider speaks the LLC-shaped interface (fetch from exact
offset), which subsumes HLC semantics; Kafka itself is gated behind an
optional import (no client library is baked into this image).
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

Row = Dict[str, Any]


class LagProbe:
    """TTL-cached consumer-lag measurement, shared by the in-process
    (realtime/llc.py) and networked (server/network_starter.py)
    consumers: latest available offset minus the consumed offset.

    ``latest_offset`` can be a stream-broker RPC (netstream/kafka), and
    the probe runs via a gauge ``set_fn`` on every metrics snapshot /
    scrape — so the measurement is cached for ``TTL_S`` (invalidated
    whenever the consumer advances, which is when the number changes on
    our side) and a failed probe degrades to the last known value
    instead of stalling the metrics surface behind a dead broker."""

    TTL_S = 5.0

    def __init__(self, stream: "StreamProvider", partition: int, offset_fn) -> None:
        self.stream = stream
        self.partition = partition
        self.offset_fn = offset_fn  # () -> consumed offset, read live
        self._cache: Optional[Tuple[Optional[int], float, int]] = None

    def __call__(self) -> Optional[int]:
        import time

        now = time.monotonic()
        offset = int(self.offset_fn())
        c = self._cache
        if c is not None and c[2] == offset and now - c[1] < self.TTL_S:
            return c[0]
        try:
            latest = int(self.stream.latest_offset(self.partition))
        except Exception:
            return c[0] if c is not None else None  # last known / unknown
        val = max(0, latest - offset)
        self._cache = (val, now, offset)
        return val


class StreamProvider:
    """Offset-addressed partition reader."""

    def partition_count(self) -> int:
        raise NotImplementedError

    def fetch(self, partition: int, offset: int, max_rows: int) -> Tuple[List[Row], int]:
        """Return (rows, next_offset) starting at ``offset``."""
        raise NotImplementedError

    def latest_offset(self, partition: int) -> int:
        raise NotImplementedError


class MemoryStreamProvider(StreamProvider):
    """In-memory partitions; producers append, consumers fetch by offset."""

    def __init__(self, num_partitions: int = 1) -> None:
        self._partitions: List[List[Row]] = [[] for _ in range(num_partitions)]
        self._lock = threading.Lock()

    def produce(self, row: Row, partition: int = 0) -> int:
        with self._lock:
            self._partitions[partition].append(row)
            return len(self._partitions[partition]) - 1

    def partition_count(self) -> int:
        return len(self._partitions)

    def fetch(self, partition: int, offset: int, max_rows: int) -> Tuple[List[Row], int]:
        with self._lock:
            rows = self._partitions[partition][offset : offset + max_rows]
        return list(rows), offset + len(rows)

    def latest_offset(self, partition: int) -> int:
        with self._lock:
            return len(self._partitions[partition])


class FileBasedStreamProvider(StreamProvider):
    """JSONL file per partition; offset = line number (the
    FileBasedStreamProviderImpl analog used by realtime tests)."""

    def __init__(self, paths: Sequence[str]) -> None:
        self.paths = list(paths)

    def partition_count(self) -> int:
        return len(self.paths)

    def _read(self, partition: int) -> List[Row]:
        rows: List[Row] = []
        with open(self.paths[partition]) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(json.loads(line))
        return rows

    def fetch(self, partition: int, offset: int, max_rows: int) -> Tuple[List[Row], int]:
        rows = self._read(partition)[offset:]
        take = rows[:max_rows]
        return take, offset + len(take)

    def latest_offset(self, partition: int) -> int:
        return len(self._read(partition))


class FlakyStreamProvider(StreamProvider):
    """Wraps a provider with injected failures: a seeded fraction of
    ``fetch`` calls raise, and successful ones may return truncated
    batches.  The ``FlakyConsumerRealtimeClusterIntegrationTest``
    analog — consumers built on the retrying consume loops must still
    ingest exactly once."""

    def __init__(self, inner: StreamProvider, fail_rate: float = 0.5, seed: int = 0) -> None:
        import random

        self.inner = inner
        self.fail_rate = fail_rate
        self._rng = random.Random(seed)
        self.failures = 0

    def partition_count(self) -> int:
        return self.inner.partition_count()

    def latest_offset(self, partition: int) -> int:
        return self.inner.latest_offset(partition)

    def fetch(self, partition: int, offset: int, max_rows: int) -> Tuple[List[Row], int]:
        if self._rng.random() < self.fail_rate:
            self.failures += 1
            raise RuntimeError("injected stream failure")
        if max_rows > 1 and self._rng.random() < 0.5:
            max_rows = self._rng.randint(1, max_rows)  # short read
        return self.inner.fetch(partition, offset, max_rows)


def stream_provider_from_config(stream_config) -> StreamProvider:
    """Build a provider from a table's StreamConfig (the
    KafkaStreamProviderConfig -> consumer factory analog), so REALTIME
    tables can be created over plain REST."""
    t = stream_config.stream_type
    props = stream_config.properties or {}
    if t == "network":
        from pinot_tpu.realtime.netstream import NetworkStreamProvider

        return NetworkStreamProvider(
            props.get("host", "127.0.0.1"), int(props["port"]), stream_config.topic
        )
    if t == "file":
        return FileBasedStreamProvider(props["paths"])
    if t == "memory":
        return MemoryStreamProvider(int(props.get("partitions", 1)))
    if t == "kafka":
        # binary wire-protocol consumer, no client library needed
        # (realtime/kafka.py, SimpleConsumerWrapper.java analog)
        from pinot_tpu.realtime.kafka import KafkaStreamProvider

        return KafkaStreamProvider(
            props.get("host", "127.0.0.1"), int(props["port"]), stream_config.topic
        )
    raise ValueError(f"unknown stream type {t!r}")


def describe_stream(provider: StreamProvider) -> Optional[Dict[str, Any]]:
    """JSON descriptor for a provider, so a restarted controller can
    reattach the stream (the ZK stream-metadata analog,
    ``common/metadata/stream/``).  Memory streams describe shape only —
    their buffered rows die with the process."""
    if isinstance(provider, FileBasedStreamProvider):
        return {"type": "file", "paths": list(provider.paths)}
    if isinstance(provider, MemoryStreamProvider):
        return {"type": "memory", "partitions": provider.partition_count()}
    describe = getattr(provider, "describe", None)
    if callable(describe):
        return describe()
    return None


def stream_from_descriptor(desc: Dict[str, Any]) -> StreamProvider:
    t = desc.get("type")
    if t == "file":
        return FileBasedStreamProvider(desc["paths"])
    if t == "memory":
        return MemoryStreamProvider(int(desc.get("partitions", 1)))
    if t == "network":
        from pinot_tpu.realtime.netstream import NetworkStreamProvider

        return NetworkStreamProvider(desc["host"], int(desc["port"]), desc["topic"])
    if t == "kafka":
        from pinot_tpu.realtime.kafka import KafkaStreamProvider

        return KafkaStreamProvider(desc["host"], int(desc["port"]), desc["topic"])
    raise ValueError(f"unknown stream descriptor {desc!r}")


