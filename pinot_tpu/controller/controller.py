"""Controller: cluster CRUD facade + REST API.

The reference controller (``ControllerStarter.java:47``) exposes REST
resources for schemas/tables/segments/instances and proxies PQL to a
broker (``PqlQueryResource.java``); uploads store the segment and write
ideal state (``PinotSegmentUploadRestletResource.java``).  Same surface
here over ``ClusterResourceManager`` + ``SegmentStore``.
"""
from __future__ import annotations

import concurrent.futures
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional
from urllib.parse import parse_qs, unquote, urlparse

from pinot_tpu.common.fencing import StaleEpochError
from pinot_tpu.common.schema import Schema
from pinot_tpu.common.tableconfig import TableConfig
from pinot_tpu.controller import dashboard
from pinot_tpu.controller.managers import (
    CrcAuditManager,
    DeepStoreScrubber,
    RetentionManager,
    SegmentStatusChecker,
    ValidationManager,
)
from pinot_tpu.controller.resource_manager import ClusterResourceManager
from pinot_tpu.controller.store import SegmentStore
from pinot_tpu.segment.immutable import ImmutableSegment
from pinot_tpu.utils.metrics import ControllerMetrics, prometheus_text

logger = logging.getLogger(__name__)


class Controller:
    def __init__(
        self,
        data_dir: str,
        start_managers: bool = False,
        lease_s: Optional[float] = None,
        fault_injector=None,
    ) -> None:
        from pinot_tpu.controller.property_store import PropertyStore

        self.property_store = PropertyStore(os.path.join(data_dir, "property_store"))
        # claim the cluster-wide fencing epoch (ZK leader-generation
        # analog): this incarnation owns the store from here on; any
        # previously-constructed controller over the same store becomes
        # a fenced zombie whose writes raise StaleEpochError
        self.epoch = self.property_store.claim_epoch()
        self.resources = ClusterResourceManager(property_store=self.property_store)
        self.store = SegmentStore(os.path.join(data_dir, "segments"))
        self.metrics = ControllerMetrics("controller")
        # pre-register the control-plane series so /metrics exposes
        # them at zero from process start
        for m in ("instanceRegistrations", "heartbeats", "instancesMarkedDead",
                  "transitionAcks", "clusterStatePolls",
                  "clusterStateCacheHits", "segmentUploads",
                  "lease.granted", "fence.staleEpochRejections",
                  "fence.leaseRejections", "fence.committerReElections"):
            self.metrics.meter(m)
        self.metrics.gauge("fence.epoch").set(self.epoch)
        from pinot_tpu.realtime.llc import RealtimeSegmentManager

        self.realtime_manager = RealtimeSegmentManager(
            self.resources, self.store, metrics=self.metrics
        )
        # arm the commit-plane fence: segmentConsumed/segmentCommit
        # carry the caller's lease epoch; a mismatch is typed-rejected
        self.realtime_manager.epoch = self.epoch
        self.retention_manager = RetentionManager(self.resources, self.store)
        self.validation_manager = ValidationManager(
            self.resources, realtime_manager=self.realtime_manager
        )
        self.status_checker = SegmentStatusChecker(self.resources)
        # correctness audit plane (ISSUE 19): periodic cross-replica
        # CRC sweep over every alive server's /debug/segments claims
        self.crc_audit = CrcAuditManager(self.resources)
        # disaster-recovery plane (ISSUE 20): background deep-store
        # scrub + reverse replication of lost/corrupt durable copies
        # from live servers' verified replicas
        self.deepstore_scrubber = DeepStoreScrubber(self.resources, self.store)
        # fetch-path feedback: servers that download CRC-failing bytes
        # report the store copy suspect through the resource manager
        self.resources.report_store_suspect = self.deepstore_scrubber.report_suspect

        from pinot_tpu.controller.stabilizer import SelfStabilizer

        # the convergence loop: re-replicates off dead/draining servers,
        # retires orphaned consuming segments, cleans the ideal state —
        # and (r15) proactively rebalances skewed placement
        self.stabilizer = SelfStabilizer(
            self.resources, realtime_manager=self.realtime_manager
        )
        # skew inputs for the rebalance planner: TTL-cached rollups of
        # the fleet's /debug/capacity (per-table cost rates) and
        # /debug/utilization (per-server busy fraction).  In-process
        # instances advertise no admin URLs, so the rollups degrade to
        # empty and placement weighs by docs alone.
        probe = _SkewProbe(self)
        self.stabilizer.cost_rate_fn = probe.cost_rates
        self.stabilizer.busy_fn = probe.busy
        # r18: tiered-residency pressure (hot bytes / HBM cap per
        # server) inflates a squeezed server's placement load so the
        # planner drains it before allocation failures start healing
        self.stabilizer.pressure_fn = probe.pressure
        # readiness gate for movement: a rebalance destination that is
        # still prewarming its compile working set (heartbeat-reported
        # warming flag) defers the old replica's trim until it is ready
        # or the prewarm window times out
        self.stabilizer.readiness_fn = (
            lambda name: not self.resources.is_instance_warming(name)
        )

        from pinot_tpu.controller.network import ParticipantGateway

        # remote-instance control plane (started by ControllerHttpServer)
        self.gateway = ParticipantGateway(
            self.resources,
            metrics=self.metrics,
            epoch=self.epoch,
            lease_s=lease_s,
            fault_injector=fault_injector,
        )
        self.gateway.on_server_available = (
            self.realtime_manager.ensure_consuming_segments
        )
        # committer liveness for the completion FSM: a committer whose
        # lease expired (partitioned away mid-upload) is re-electable
        self.realtime_manager.completion.lease_checker = (
            self.gateway.server_lease_valid
        )

        # SLO & tail-latency attribution plane (ISSUE 11): one history
        # thread over the controller + stabilizer registries (served at
        # /debug/history); dead servers / stabilizer repairs spotted on
        # its tick dump a flight-recorder bundle (disabled unless
        # PINOT_TPU_FLIGHTREC_DIR is set)
        from pinot_tpu.utils.flightrec import FlightRecorder
        from pinot_tpu.utils.timeseries import HistoryRecorder

        self.history = HistoryRecorder(
            [self.metrics, self.stabilizer.metrics], metrics=self.metrics
        )
        # gauges like aliveServers refresh lazily; the provider keeps
        # every history sample current without a second thread
        self.history.register_provider(lambda: self._refresh_gauges() or {})
        self.flightrec = FlightRecorder(
            "controller",
            "controller",
            metrics=self.metrics,
            sources={
                "history": lambda: self.history.query(window_s=900),
                "metrics": self.metrics_snapshot,
                "stabilizer": lambda: self.stabilizer.debug_snapshot(),
            },
        )
        self._last_notable = 0
        self.history.add_tick_hook(self._history_tick)

        self._recover()

        if start_managers:
            self.retention_manager.start()
            self.validation_manager.start()
            self.status_checker.start()
            self.crc_audit.start()
            self.deepstore_scrubber.start()
            self.stabilizer.start()

    def _recover(self) -> None:
        """Reload cluster metadata from the property store after a
        restart (the reference recovers everything from ZK:
        ``PinotHelixResourceManager.java:103``).  External views start
        empty — they refill as participants re-register and replay
        their ideal-state transitions (``reconcile_instance``); LLC
        consumption resumes from the checkpointed offsets via
        ``RealtimeSegmentManager.recover_table``."""
        from pinot_tpu.segment.immutable import SegmentMetadata

        ps = self.property_store
        res = self.resources
        for name in ps.list_keys("schemas"):
            rec = ps.get("schemas", name)
            if rec is not None:
                with res._lock:
                    res.schemas[name] = Schema.from_json(rec)
        recovered_tables: List[str] = []
        for physical in ps.list_keys("tables"):
            rec = ps.get("tables", physical)
            if rec is None:
                continue
            config = TableConfig.from_json(rec)
            with res._lock:
                res.table_configs[physical] = config
                res.ideal_states.setdefault(physical, {})
                res.external_views.setdefault(physical, {})
            recovered_tables.append(physical)
            ideal = ps.get("idealstates", physical)
            if ideal:
                with res._lock:
                    res.ideal_states[physical] = {
                        seg: dict(replicas) for seg, replicas in ideal.items()
                    }
            for seg in ps.list_keys(f"segments/{physical}"):
                rec = ps.get(f"segments/{physical}", seg)
                if rec is None:
                    continue
                info: Dict[str, Any] = {
                    k: v for k, v in rec.items() if k != "metadata"
                }
                if rec.get("metadata") is not None:
                    info["metadata"] = SegmentMetadata.from_json(rec["metadata"])
                with res._lock:
                    res.segment_metadata[(physical, seg)] = info
        for physical in recovered_tables:
            config = res.table_configs[physical]
            schema = res.get_schema(config.raw_name)
            if schema is not None and config.table_type == "REALTIME":
                if not self.realtime_manager.recover_table(physical, config, schema):
                    logger.error(
                        "realtime table %s recovered without a stream "
                        "descriptor: consumption cannot resume (provider "
                        "was not describable); re-create the table",
                        physical,
                    )
        # draining flags were reloaded by ClusterResourceManager from the
        # property store's "instances" namespace: an in-flight drain (or
        # a partially-applied stabilizer plan, which is just persisted
        # ideal-state writes) resumes exactly where the crash left it —
        # re-registering servers replay transitions, the next stabilizer
        # round re-derives the remaining work from ideal vs external view
        if res._draining_flags:
            logger.info(
                "recovered draining flags for %s", sorted(res._draining_flags)
            )
        if recovered_tables:
            logger.info(
                "recovered %d tables, %d schemas from property store",
                len(recovered_tables),
                len(res.schemas),
            )

    # -- CRUD -----------------------------------------------------------
    def add_schema(self, schema: Schema) -> None:
        existing = self.resources.get_schema(schema.schema_name)
        evolving = existing is not None and existing != schema
        self.resources.add_schema(schema)
        if evolving:
            # schema evolution: reload every table built on this schema
            # so already-loaded segments pick up default columns for the
            # added fields (reference operators call segment reload
            # after a schema change; here it is automatic), and swap the
            # realtime manager's stored schema so the next consuming
            # segment rollover ingests new columns instead of dropping
            # their streamed values
            self.realtime_manager.update_schema(schema.schema_name, schema)
            for physical in self.resources.tables_of_schema(schema.schema_name):
                self.resources.reload_table(physical)

    def add_table(self, config: TableConfig) -> str:
        if self.resources.get_schema(config.raw_name) is None:
            raise ValueError(f"no schema named {config.raw_name!r}; upload the schema first")
        self.resources.validate_tenants(config)
        return self.resources.add_table(config)

    def rebalance_table(self, table_physical: str, dry_run: bool = False) -> Dict[str, Any]:
        return self.resources.rebalance_table(table_physical, dry_run=dry_run)

    # -- drain / decommission -------------------------------------------
    def drain_status(self, name: str) -> Dict[str, Any]:
        """Drained-vs-remaining accounting for one instance: the rolling
        -restart loop polls this until ``drained`` flips true."""
        remaining = self.resources.segments_on(name)
        total = sum(len(v) for v in remaining.values())
        inst = self.resources.instances.get(name)
        if inst is None and not remaining and name not in self.resources._draining_flags:
            # never registered, holds nothing, no recovered drain flag: a
            # typo'd name must error, not report drained=true to a
            # rolling-restart loop about to bounce the REAL server
            raise KeyError(f"unknown instance {name!r}")
        return {
            "instance": name,
            "draining": name in self.resources._draining_flags,
            "alive": inst.alive if inst is not None else False,
            "remainingSegments": total,
            "remaining": remaining,
            "drained": total == 0,
        }

    def drain_instance(self, name: str) -> Dict[str, Any]:
        """Mark an instance draining: brokers stop routing NEW queries
        to it (in-flight ones finish), the stabilizer migrates its
        replicas off, and the returned status reports progress.
        Idempotent — a rolling restart is drain -> poll until drained ->
        restart the process -> undrain."""
        self.resources.set_instance_draining(name, True)
        return self.drain_status(name)

    def undrain_instance(self, name: str) -> Dict[str, Any]:
        """Explicitly re-admit a drained instance to routing/placement
        (registration alone never clears the flag — a controller restart
        mid-drain must not silently resurrect the instance)."""
        self.resources.set_instance_draining(name, False)
        return self.drain_status(name)


    def add_realtime_table(self, config: TableConfig, stream) -> str:
        """Create a REALTIME table and open its first CONSUMING segments
        (PinotLLCRealtimeSegmentManager analog)."""
        schema = self.resources.get_schema(config.raw_name)
        if schema is None:
            raise ValueError(f"no schema named {config.raw_name!r}; upload the schema first")
        self.resources.validate_tenants(config)
        return self.realtime_manager.setup_table(config, schema, stream)

    def _check_storage_quota(
        self, table_physical: str, segment_name: str, incoming_bytes: int
    ) -> None:
        """Raise BEFORE the store is touched when the upload would push
        the table's durable copy past its quota (StorageQuotaChecker
        analog); a rejected upload — fresh or refresh — leaves the
        previous copy intact."""
        config = self.resources.table_configs.get(table_physical)
        quota = config.quota.storage_bytes() if config is not None else None
        if quota is None:
            return
        used = self.store.table_size_bytes(table_physical)
        # a refresh replaces the old copy, so it doesn't double-count
        used -= self.store.segment_size_bytes(table_physical, segment_name)
        if used + incoming_bytes > quota:
            raise ValueError(
                f"storage quota exceeded for {table_physical}: "
                f"{used} used + {incoming_bytes} incoming > {quota} quota"
            )

    def upload_segment(self, table_physical: str, segment: ImmutableSegment) -> List[str]:
        """Store the segment durably and drive replicas ONLINE."""
        import tempfile

        from pinot_tpu.segment.format import SEGMENT_FILE_NAME, write_segment

        config = self.resources.table_configs.get(table_physical)
        if config is None or config.quota.storage_bytes() is None:
            path = self.store.save(table_physical, segment)
        else:
            # serialize once into a staging dir, quota-check the real
            # size, then move the bytes into the store
            with tempfile.TemporaryDirectory() as td:
                write_segment(segment, td)
                staged = os.path.join(td, SEGMENT_FILE_NAME)
                self._check_storage_quota(
                    table_physical, segment.segment_name, os.path.getsize(staged)
                )
                path = self.store.save_file(
                    table_physical, segment.segment_name, staged
                )
        self.metrics.meter("segmentUploads").mark()
        return self.resources.add_segment(
            table_physical,
            segment.metadata,
            {"dir": path, "downloadUri": "file://" + os.path.abspath(path)},
        )

    def upload_segment_bytes(
        self, table_physical: str, data: bytes, servers: Optional[List[str]] = None
    ) -> List[str]:
        """HTTP upload path: raw segment-file bytes -> store + assign.
        The received payload is the exact on-disk size, so the quota
        check needs no extra serialization.  ``servers`` pins the
        assignment (HLC uploads keep a server-owned segment on its
        consuming server)."""
        import tempfile

        from pinot_tpu.segment.format import SEGMENT_FILE_NAME, read_segment

        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, SEGMENT_FILE_NAME)
            with open(path, "wb") as f:
                f.write(data)
            segment = read_segment(td)
            self._check_storage_quota(table_physical, segment.segment_name, len(data))
            stored = self.store.save_file(table_physical, segment.segment_name, path)
        self.metrics.meter("segmentUploads").mark()
        return self.resources.add_segment(
            table_physical,
            segment.metadata,
            {"dir": stored, "downloadUri": "file://" + os.path.abspath(stored)},
            servers=servers,
        )

    def delete_segment(self, table_physical: str, segment_name: str) -> None:
        self.resources.delete_segment(table_physical, segment_name)
        self.store.delete(table_physical, segment_name)

    def delete_table(self, table_physical: str) -> None:
        self.resources.delete_table(table_physical)

    # -- observability --------------------------------------------------
    def _refresh_gauges(self) -> None:
        insts = self.resources.instances_snapshot()
        self.metrics.gauge("aliveServers").set(
            sum(1 for i in insts if i.role == "server" and i.alive)
        )
        self.metrics.gauge("aliveBrokers").set(
            sum(1 for i in insts if i.role == "broker" and i.alive)
        )
        self.metrics.gauge("deadInstances").set(sum(1 for i in insts if not i.alive))
        self.metrics.gauge("tables").set(len(self.resources.tables()))

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Controller-side registries as JSON (``/debug/metrics``):
        control-plane traffic plus the validation/status-checker
        per-table health gauges."""
        self._refresh_gauges()
        return {
            "controller": self.metrics.snapshot(),
            "validation": self.validation_manager.metrics.snapshot(),
            "segmentStatus": self.status_checker.metrics.snapshot(),
            "stabilizer": self.stabilizer.metrics.snapshot(),
            "retention": self.retention_manager.metrics.snapshot(),
            "deepstore": self.deepstore_scrubber.metrics.snapshot(),
            "durability": self.property_store.metrics.snapshot(),
        }

    def metrics_text(self) -> str:
        """Prometheus exposition of every controller registry."""
        self._refresh_gauges()
        return prometheus_text(
            [
                self.metrics,
                self.validation_manager.metrics,
                self.status_checker.metrics,
                self.stabilizer.metrics,
                self.retention_manager.metrics,
                self.deepstore_scrubber.metrics,
                self.property_store.metrics,
            ]
        )

    def _history_tick(self, now: float) -> None:
        """Flight-recorder trigger on the history cadence: servers
        declared dead or stabilizer repairs since the last sample are
        the cluster-level notable events."""
        total = (
            self.metrics.meter("instancesMarkedDead").count
            + self.stabilizer.metrics.meter("stabilizer.replicasAdded").count
            + self.stabilizer.metrics.meter(
                "stabilizer.consumingReassigned"
            ).count
        )
        delta = total - self._last_notable
        self._last_notable = total
        if delta > 0:
            self.flightrec.maybe_dump(
                "serverDeathOrHeal", {"notableEventsThisTick": delta}
            )

    def stop(self) -> None:
        self.history.stop()
        self.retention_manager.stop()
        self.validation_manager.stop()
        self.status_checker.stop()
        self.crc_audit.stop()
        self.deepstore_scrubber.stop()
        self.stabilizer.stop()
        self.property_store.close()


def cost_rates_from_capacity(capacity: Dict[str, Any]) -> Dict[str, float]:
    """Per-table docsScanned 1-minute rates out of a ``/debug/capacity``
    rollup — the cost axis of the rebalance planner's doc-x-cost
    placement weight."""
    out: Dict[str, float] = {}
    for table, entry in (capacity.get("tables") or {}).items():
        try:
            out[table] = float(entry.get("docsScannedRate1m") or 0.0)
        except (TypeError, ValueError):
            continue
    return out


def tier_pressure_from_capacity(capacity: Dict[str, Any]) -> Dict[str, float]:
    """Per-server residency pressure (hot-tier bytes as a fraction of
    the configured HBM cap, 0..1) out of a ``/debug/capacity`` rollup —
    the rebalance planner's memory axis.  Servers without a residency
    section (no cap configured, or pre-r18) simply don't appear."""
    out: Dict[str, float] = {}
    for name, entry in (capacity.get("servers") or {}).items():
        res = entry.get("residency") or {}
        try:
            p = float(res.get("pressure") or 0.0)
        except (TypeError, ValueError):
            continue
        if p > 0:
            out[name] = p
    return out


def busy_from_utilization(util: Dict[str, Any]) -> Dict[str, float]:
    """Per-server device busy fractions out of a ``/debug/utilization``
    rollup — the rebalance planner's destination tiebreak (prefer the
    idlest cold server)."""
    out: Dict[str, float] = {}
    for name, entry in (util.get("servers") or {}).items():
        occ = (entry.get("device") or {}).get("occupancy") or {}
        try:
            out[name] = float(occ.get("busyFraction") or 0.0)
        except (TypeError, ValueError):
            continue
    return out


class _SkewProbe:
    """TTL-cached skew inputs for the stabilizer's rebalance planner.

    The planner evaluates every round (the 2s stabilizer cadence), but
    the fleet rollups behind it cost one HTTP fan-out each — so the
    probe refreshes at most every ``ttl_s`` seconds and serves cached
    maps in between.  Any failure degrades to empty maps (docs-only
    weighting); a dead server's rollup entry must never stall the
    convergence loop."""

    def __init__(self, ctrl: "Controller", ttl_s: float = 30.0) -> None:
        self.ctrl = ctrl
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._at = 0.0
        self._rates: Dict[str, float] = {}
        self._busy: Dict[str, float] = {}
        self._pressure: Dict[str, float] = {}

    def _refresh(self) -> None:
        import time as _time

        with self._lock:
            now = _time.monotonic()
            if now - self._at < self.ttl_s:
                return
            self._at = now
        try:
            capacity = collect_capacity(self.ctrl, timeout_s=1.5)
            self._rates = cost_rates_from_capacity(capacity)
            self._pressure = tier_pressure_from_capacity(capacity)
            self._busy = busy_from_utilization(
                collect_utilization(self.ctrl, timeout_s=1.5)
            )
        except Exception:
            logger.warning("skew-probe rollup failed", exc_info=True)

    def cost_rates(self) -> Dict[str, float]:
        self._refresh()
        return self._rates

    def busy(self) -> Dict[str, float]:
        self._refresh()
        return self._busy

    def pressure(self) -> Dict[str, float]:
        self._refresh()
        return self._pressure


def collect_cluster_metrics(ctrl: "Controller", timeout_s: float = 3.0) -> Dict[str, Any]:
    """Cluster-wide metrics snapshot: the controller's own registries
    plus ``/debug/metrics`` fetched from every alive instance that
    advertises an HTTP surface (brokers' query port, servers' admin
    port).  Unreachable instances degrade to an ``error`` entry instead
    of failing the aggregate."""
    import concurrent.futures
    import urllib.error
    import urllib.request

    def fetch(inst) -> Dict[str, Any]:
        entry: Dict[str, Any] = {"role": inst.role, "url": inst.url}
        try:
            with urllib.request.urlopen(
                inst.url.rstrip("/") + "/debug/metrics", timeout=timeout_s
            ) as r:
                entry["metrics"] = json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            entry["error"] = str(e)
        return entry

    out: Dict[str, Any] = {"controller": ctrl.metrics_snapshot(), "instances": {}}
    targets = [
        i for i in ctrl.resources.instances_snapshot() if i.alive and i.url
    ]
    if targets:
        # concurrent fetches: a few blackholed instances must cost ONE
        # timeout, not one each, or the dashboard page crawls
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(16, len(targets))
        ) as pool:
            for inst, entry in zip(targets, pool.map(fetch, targets)):
                out["instances"][inst.name] = entry
    return out


def collect_capacity(ctrl: "Controller", timeout_s: float = 3.0) -> Dict[str, Any]:
    """Cluster-wide capacity & cost rollup (``/debug/capacity``): every
    alive server's HBM staging ledger and ingest lag next to every
    broker's per-table cost rates — one page answering "who is burning
    the cluster" and "how much headroom is left".

    Sources: server ``/debug/metrics`` (= ``ServerInstance.status()``,
    which carries the ``hbm`` ledger snapshot and the ``ingest.lag.*``
    gauges) and broker ``/debug/metrics`` (whose ``table.*.docsScanned``
    / ``table.*.bytesScanned`` meters are the per-table attribution).
    Unreachable instances degrade to an ``error`` entry.  Note: the HBM
    ledger is per-process, so in-process multi-server harnesses report
    the same figure on each instance (networked servers are separate
    processes and sum correctly)."""
    cm = collect_cluster_metrics(ctrl, timeout_s=timeout_s)
    servers: Dict[str, Any] = {}
    tables: Dict[str, Dict[str, Any]] = {}
    unreachable: Dict[str, Any] = {}
    total_staged = 0
    total_lag = 0
    for name, entry in sorted((cm.get("instances") or {}).items()):
        role = entry.get("role")
        if entry.get("error"):
            # EVERY unreachable instance is reported: a dead broker
            # means the per-table attribution below is partial, and the
            # page must say so rather than reading as "no cost recorded"
            unreachable[name] = {"role": role, "error": entry["error"]}
            if role == "server":
                servers[name] = {"error": entry["error"]}
            continue
        payload = entry.get("metrics") or {}
        if role == "server":
            hbm = payload.get("hbm") or {}
            snap = payload.get("metrics") or {}
            gauges = snap.get("gauges") or {}
            meters = snap.get("meters") or {}
            lag = {
                k[len("ingest.lag."):]: v
                for k, v in gauges.items()
                if k.startswith("ingest.lag.") and isinstance(v, (int, float))
            }
            rows = meters.get("ingest.rowsConsumed") or {}
            cost_rows = meters.get("cost.docsScanned") or {}
            cost_bytes = meters.get("cost.bytesScanned") or {}
            servers[name] = {
                "hbm": {
                    k: hbm.get(k)
                    for k in (
                        "stagedBytes",
                        "highWatermarkBytes",
                        "stagedTables",
                        "evictions",
                        "evictedBytes",
                        "qinputCacheBytes",
                        "byTable",
                    )
                },
                "ingestLag": lag,
                "ingestRows": rows,
                "costDocsScanned": cost_rows,
                "costBytesScanned": cost_bytes,
            }
            res = payload.get("residency") or {}
            if res:
                # tiered-residency view (r18): how hard this server's
                # hot tier presses against its HBM cap, and how much of
                # its working set has been pushed down-tier
                servers[name]["residency"] = {
                    k: res.get(k)
                    for k in (
                        "pressure",
                        "hbmCapBytes",
                        "hotBytes",
                        "warmBytes",
                        "coldBytes",
                        "hotTables",
                        "warmTables",
                        "coldTables",
                    )
                }
            total_staged += int(hbm.get("stagedBytes") or 0)
            total_lag += int(sum(lag.values()))
        elif role == "broker":
            meters = (payload.get("meters") or {})
            for mname, m in meters.items():
                if not mname.startswith("table.") or "." not in mname[len("table."):]:
                    continue
                tname, metric = mname[len("table."):].rsplit(".", 1)
                if metric not in ("docsScanned", "bytesScanned"):
                    continue
                t = tables.setdefault(tname, {})
                t[metric] = t.get(metric, 0) + int(m.get("count") or 0)
                t[f"{metric}Rate1m"] = round(
                    t.get(f"{metric}Rate1m", 0.0) + float(m.get("rate1m") or 0.0), 3
                )
    return {
        "totals": {
            "stagedBytes": total_staged,
            "ingestLagRows": total_lag,
            "servers": len(servers),
            "tables": len(tables),
        },
        "servers": servers,
        "tables": tables,
        "unreachable": unreachable,
    }


def collect_workload(
    ctrl: "Controller",
    timeout_s: float = 3.0,
    n: int = 20,
    tables=None,
) -> Dict[str, Any]:
    """Cluster-wide workload roll-up (``/debug/workload``): every alive
    broker's per-plan-digest registry merged by digest — counts and
    cost sums add, summaries/tables/exemplars are first-writer — then
    re-ranked by frequency and by cost.  The fleet-level answer to
    "which plan shapes dominate, and which should batched serving
    target first?" — and, with ``tables``, the prewarm feed a restarted
    server pulls for the tables it hosts (``?n=&tables=``).
    Unreachable brokers degrade to an ``unreachable`` entry."""
    import urllib.error
    import urllib.request

    from pinot_tpu.engine.plandigest import _raw_table

    wanted = (
        None if tables is None else {_raw_table(t) for t in tables}
    )
    merged: Dict[str, Dict[str, Any]] = {}
    unreachable: Dict[str, str] = {}
    brokers = [
        i
        for i in ctrl.resources.instances_snapshot()
        if i.role == "broker" and i.alive and i.url
    ]

    def fetch(inst):
        try:
            # top=1024 (above the registry capacity) returns the FULL
            # per-broker registry: merging truncated top-20 slices
            # would undercount any digest outside one broker's head
            with urllib.request.urlopen(
                inst.url.rstrip("/") + "/debug/workload?top=1024",
                timeout=timeout_s,
            ) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            return {"_error": str(e)}

    results = []
    if brokers:
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(8, len(brokers))
        ) as pool:
            results = list(pool.map(fetch, brokers))
    total_recorded = 0
    for inst, snap in zip(brokers, results):
        if "_error" in snap:
            unreachable[inst.name] = snap["_error"]
            continue
        total_recorded += int(snap.get("totalRecorded") or 0)
        seen: set = set()
        for plan in (snap.get("topByCount") or []) + (snap.get("topByCost") or []):
            digest = plan.get("digest")
            if not digest or digest in seen:
                continue  # a digest appears in both rankings: merge once
            seen.add(digest)
            if wanted is not None and _raw_table(plan.get("table", "")) not in wanted:
                continue
            m = merged.get(digest)
            if m is None:
                m = merged[digest] = {
                    "digest": digest,
                    "summary": plan.get("summary", ""),
                    "table": plan.get("table", ""),
                    # literals-erased exemplar (first broker wins): what
                    # a prewarming server re-parses to rebuild the shape
                    "exemplarPql": plan.get("exemplarPql", ""),
                    "count": 0,
                    "shedCount": 0,
                    "failedCount": 0,
                    "docsScanned": 0,
                    "cost": {},
                    "brokers": [],
                }
            elif not m.get("exemplarPql") and plan.get("exemplarPql"):
                m["exemplarPql"] = plan["exemplarPql"]
            m["count"] += int(plan.get("count") or 0)
            m["shedCount"] += int(plan.get("shedCount") or 0)
            m["failedCount"] += int(plan.get("failedCount") or 0)
            m["docsScanned"] += int(plan.get("docsScanned") or 0)
            for k, v in (plan.get("cost") or {}).items():
                m["cost"][k] = m["cost"].get(k, 0) + v
            m["brokers"].append(inst.name)

    # the ONE cost-ranking formula, shared with the broker's registry
    from pinot_tpu.utils.planstats import PlanStatsStore

    cost_key = PlanStatsStore._cost_key

    plans = list(merged.values())
    k = max(1, int(n))
    return {
        "brokers": len(brokers),
        "digests": len(plans),
        "totalRecorded": total_recorded,
        "topByCount": sorted(plans, key=lambda d: -d["count"])[:k],
        "topByCost": sorted(plans, key=cost_key, reverse=True)[:k],
        "unreachable": unreachable,
    }


def collect_slo(ctrl: "Controller", timeout_s: float = 3.0) -> Dict[str, Any]:
    """Fleet SLO rollup (``/debug/slo`` on the controller): every alive
    broker's ``/debug/slo`` merged per table.  Each broker evaluates
    burn rates over its OWN traffic, so the fleet view takes the WORST
    burn per table across brokers (the one an operator should look at)
    and keeps the per-broker breakdown verbatim underneath.  A table is
    fleet-burning if ANY broker reports it burning.  Unreachable
    brokers degrade to an ``unreachable`` entry (partial rollups say
    so)."""
    import urllib.error
    import urllib.request

    brokers = [
        i
        for i in ctrl.resources.instances_snapshot()
        if i.role == "broker" and i.alive and i.url
    ]

    def fetch(inst):
        try:
            with urllib.request.urlopen(
                inst.url.rstrip("/") + "/debug/slo", timeout=timeout_s
            ) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            return {"_error": str(e)}

    results = []
    if brokers:
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(8, len(brokers))
        ) as pool:
            results = list(pool.map(fetch, brokers))

    tables: Dict[str, Dict[str, Any]] = {}
    unreachable: Dict[str, str] = {}
    config: Dict[str, Any] = {}
    for inst, snap in zip(brokers, results):
        if "_error" in snap:
            unreachable[inst.name] = snap["_error"]
            continue
        config = config or (snap.get("config") or {})
        for table, entry in (snap.get("tables") or {}).items():
            t = tables.get(table)
            if t is None:
                t = tables[table] = {
                    "burnRate5m": 0.0,
                    "burnRate1h": 0.0,
                    "burning": False,
                    "objective": entry.get("objective"),
                    "byBroker": {},
                }
            t["burnRate5m"] = max(
                t["burnRate5m"], float(entry.get("burnRate5m") or 0.0)
            )
            t["burnRate1h"] = max(
                t["burnRate1h"], float(entry.get("burnRate1h") or 0.0)
            )
            t["burning"] = t["burning"] or bool(entry.get("burning"))
            t["byBroker"][inst.name] = {
                "burnRate5m": entry.get("burnRate5m"),
                "burnRate1h": entry.get("burnRate1h"),
                "burning": entry.get("burning"),
                "windows": entry.get("windows"),
            }
    burning = sorted(t for t, e in tables.items() if e["burning"])
    ranked = sorted(
        tables.items(),
        key=lambda kv: -max(kv[1]["burnRate5m"], kv[1]["burnRate1h"]),
    )
    return {
        "brokers": len(brokers),
        "config": config,
        "tables": tables,
        "burningTables": burning,
        "worstBurning": [t for t, _ in ranked[:10]],
        "unreachable": unreachable,
    }


def collect_utilization(
    ctrl: "Controller", timeout_s: float = 3.0, top_k: int = 10
) -> Dict[str, Any]:
    """Fleet device-utilization rollup (``/debug/utilization``): every
    alive server's ``/debug/device`` snapshot included VERBATIM under
    ``servers.<name>.device`` — the totals below are computed from
    exactly those snapshots, so the rollup always equals what it
    fetched (the consistency the tier-1 acceptance test asserts) —
    plus fleet aggregates (summed transfers, combined achieved rates
    over the recent windows, occupancy spread) and the top-K
    UNDERutilized executed plan shapes across every server's
    ``/debug/plans`` registry.  A shape with heavy device time and a
    low roofline fraction is exactly what the upcoming batched-serving
    and multichip PRs should target first; this is their gating
    measurement substrate.  Unreachable servers degrade to an
    ``unreachable`` entry (partial rollups say so)."""
    import urllib.error
    import urllib.request

    targets = [
        i
        for i in ctrl.resources.instances_snapshot()
        if i.role == "server" and i.alive and i.url
    ]

    def fetch(inst):
        # the two GETs degrade independently: a server whose plans
        # registry times out still contributes its device snapshot
        # (only a failed DEVICE fetch marks it unreachable)
        out: Dict[str, Any] = {}
        base = inst.url.rstrip("/")
        try:
            with urllib.request.urlopen(
                base + "/debug/device", timeout=timeout_s
            ) as r:
                out["device"] = json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            out["_error"] = str(e)
            return out
        # full registry head ranked by cost: the underutilized-shape
        # scan wants the expensive shapes, not the frequent ones
        try:
            with urllib.request.urlopen(
                base + "/debug/plans?by=cost&top=1024", timeout=timeout_s
            ) as r:
                out["plans"] = json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            out["plansError"] = str(e)
        return out

    results = []
    if targets:
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(16, len(targets))
        ) as pool:
            results = list(pool.map(fetch, targets))

    servers: Dict[str, Any] = {}
    unreachable: Dict[str, str] = {}
    totals = {
        "h2dBytes": 0,
        "d2hBytes": 0,
        "deviceMs": 0.0,
        "deviceBytes": 0,
        "queries": 0,
    }
    busy: List[float] = []
    fractions: List[float] = []
    profiles_active = 0
    shapes: List[Dict[str, Any]] = []
    # transfer counters are per-PROCESS (like the staging cache they
    # instrument): servers sharing one process all report the same
    # cumulative numbers, so the fleet total counts each processToken
    # once instead of multiplying by co-resident servers
    seen_transfer_tokens: set = set()
    for inst, snap in zip(targets, results):
        if "_error" in snap:
            unreachable[inst.name] = snap["_error"]
            continue
        dev = snap.get("device") or {}
        servers[inst.name] = {"device": dev}
        if "plansError" in snap:
            servers[inst.name]["plansError"] = snap["plansError"]
        occ = dev.get("occupancy") or {}
        if occ:
            busy.append(float(occ.get("busyFraction") or 0.0))
        tr = dev.get("transfers") or {}
        token = tr.get("processToken") or f"_anon-{inst.name}"
        if token not in seen_transfer_tokens:
            seen_transfer_tokens.add(token)
            totals["h2dBytes"] += int(tr.get("h2dBytes") or 0)
            totals["d2hBytes"] += int(tr.get("d2hBytes") or 0)
        recent = dev.get("recent") or {}
        totals["deviceMs"] = round(
            totals["deviceMs"] + float(recent.get("deviceMs") or 0.0), 3
        )
        totals["deviceBytes"] += int(recent.get("deviceBytes") or 0)
        totals["queries"] += int(recent.get("queries") or 0)
        if recent.get("rooflineFraction") is not None:
            fractions.append(float(recent["rooflineFraction"]))
        if (dev.get("profiler") or {}).get("active"):
            profiles_active += 1
        for plan in (snap.get("plans") or {}).get("plans") or []:
            roof = plan.get("roofline")
            if not roof:
                continue  # never ran on device: nothing to rank
            shapes.append(
                {
                    "server": inst.name,
                    "digest": plan.get("digest"),
                    "summary": plan.get("summary", ""),
                    "table": plan.get("table", ""),
                    "count": plan.get("count", 0),
                    "deviceMs": roof.get("deviceMs", 0),
                    "deviceBytes": roof.get("deviceBytes", 0),
                    "achievedBytesPerSec": roof.get("achievedBytesPerSec", 0),
                    "rooflineFraction": roof.get("rooflineFraction"),
                }
            )

    # least-utilized first: shapes with a declared-peak fraction rank
    # before unknown-peak shapes (ranked by raw achieved bytes/s) —
    # ties broken toward the shapes burning the most device time,
    # which are the ones worth fixing first
    def _under_key(s: Dict[str, Any]):
        f = s.get("rooflineFraction")
        if f is not None:
            return (0, f, -float(s.get("deviceMs") or 0))
        return (1, float(s.get("achievedBytesPerSec") or 0),
                -float(s.get("deviceMs") or 0))

    ms = totals["deviceMs"]
    return {
        "servers": servers,
        "totals": dict(
            totals,
            achievedBytesPerSec=(
                round(totals["deviceBytes"] * 1000.0 / ms, 3) if ms > 0 else 0.0
            ),
        ),
        "occupancy": {
            "servers": len(busy),
            "meanBusyFraction": (
                round(sum(busy) / len(busy), 6) if busy else 0.0
            ),
            "maxBusyFraction": round(max(busy), 6) if busy else 0.0,
        },
        "rooflineFraction": round(max(fractions), 6) if fractions else None,
        "profilesActive": profiles_active,
        "underutilizedPlans": sorted(shapes, key=_under_key)[:top_k],
        "unreachable": unreachable,
    }


def _split_path(path: str) -> Optional[List[str]]:
    """URL-decoded path segments, or None for segments that would
    traverse the filesystem when joined into store paths (%2F / '..')."""
    parts = [unquote(p) for p in path.split("/") if p]
    for p in parts:
        if "/" in p or "\\" in p or p in (".", ".."):
            return None
    return parts


def _alive_broker_urls(resources: ClusterResourceManager) -> List[str]:
    return [
        i.url
        for i in resources.instances_snapshot()
        if i.role == "broker" and i.alive and i.url
    ]


def _proxy_pql(ctrl: Controller, pql: str, trace: bool = False) -> Dict[str, Any]:
    """Forward a PQL query to an alive broker and return its JSON
    response (``PqlQueryResource.java`` — the controller-side query
    proxy used by the dashboard's query console). Brokers are tried in
    random order with failover, as the reference picks a random broker."""
    import random
    import urllib.error
    import urllib.request

    brokers = _alive_broker_urls(ctrl.resources)
    if not brokers:
        return {"error": "no alive broker registered"}
    random.shuffle(brokers)
    last_err: Optional[Exception] = None
    for url in brokers:
        req = urllib.request.Request(
            url.rstrip("/") + "/query",
            data=json.dumps({"pql": pql, "trace": trace}).encode("utf-8"),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            # ValueError covers JSONDecodeError from a non-broker process
            # squatting on a stale registration's port
            last_err = e
    return {"error": f"all brokers failed: {last_err}"}


class ControllerHttpServer:
    """REST front (restlet resources analog): schemas, tables, segments,
    ideal/external views, health."""

    def __init__(self, controller: Controller, host: str = "127.0.0.1", port: int = 0):
        ctrl = controller

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _respond(self, payload: Any, status: int = 200) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_json(self) -> Dict[str, Any]:
                n = int(self.headers.get("Content-Length", "0"))
                return json.loads(self.rfile.read(n) or b"{}")

            def _respond_html(self, html: str) -> None:
                body = html.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _respond_text(self, text: str) -> None:
                body = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _respond_bytes(self, data: bytes) -> None:
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _respond_stale(self, e: StaleEpochError) -> None:
                # typed fencing rejection (409 Conflict): the caller —
                # or this controller — is a fenced-off former
                # authority; nothing was mutated
                return self._respond(
                    {
                        "error": str(e),
                        "errorType": "StaleEpochError",
                        "staleEpoch": e.stale,
                        "currentEpoch": e.current,
                    },
                    409,
                )

            def do_GET(self):
                url = urlparse(self.path)
                parts = _split_path(url.path)
                if parts is None:
                    return self._respond({"error": "bad path"}, 400)
                try:
                    if not parts or parts == ["dashboard"]:
                        return self._respond_html(dashboard.render_home(ctrl))
                    if parts == ["dashboard", "query"]:
                        return self._respond_html(dashboard.render_query_console())
                    if len(parts) == 3 and parts[:2] == ["dashboard", "table"]:
                        if parts[2] not in ctrl.resources.tables():
                            return self._respond({"error": "table not found"}, 404)
                        return self._respond_html(dashboard.render_table(ctrl, parts[2]))
                    if parts == ["pql"]:
                        qs = parse_qs(url.query)
                        pql = (qs.get("pql") or [""])[0]
                        trace = (qs.get("trace") or ["false"])[0].lower() == "true"
                        return self._respond(_proxy_pql(ctrl, pql, trace))
                    if parts == ["health"]:
                        return self._respond({"status": "ok"})
                    if parts == ["metrics"]:
                        # Prometheus text exposition (scrape target)
                        return self._respond_text(ctrl.metrics_text())
                    if parts == ["debug", "metrics"]:
                        return self._respond(ctrl.metrics_snapshot())
                    if parts == ["debug", "clustermetrics"]:
                        return self._respond(collect_cluster_metrics(ctrl))
                    if parts == ["debug", "capacity"]:
                        return self._respond(collect_capacity(ctrl))
                    if parts == ["dashboard", "capacity"]:
                        return self._respond_html(
                            dashboard.render_capacity(ctrl, collect_capacity(ctrl))
                        )
                    if parts == ["debug", "workload"]:
                        # ?n= caps the top-K rankings; ?tables=a,b
                        # narrows to those tables (the prewarm feed a
                        # restarted server pulls at segment-load time)
                        qs = parse_qs(url.query)
                        try:
                            n = int((qs.get("n") or qs.get("top") or ["20"])[0])
                        except ValueError:
                            n = 20
                        raw_tables = (qs.get("tables") or [""])[0]
                        tables = [
                            t.strip()
                            for t in raw_tables.split(",")
                            if t.strip()
                        ] or None
                        return self._respond(
                            collect_workload(ctrl, n=n, tables=tables)
                        )
                    if parts == ["debug", "utilization"]:
                        return self._respond(collect_utilization(ctrl))
                    if parts == ["dashboard", "utilization"]:
                        return self._respond_html(
                            dashboard.render_utilization(
                                ctrl, collect_utilization(ctrl)
                            )
                        )
                    if parts == ["dashboard", "workload"]:
                        return self._respond_html(
                            dashboard.render_workload(ctrl, collect_workload(ctrl))
                        )
                    if parts == ["debug", "history"]:
                        # bounded metric time series (utils/timeseries.py):
                        # ?series= comma-separated name prefixes,
                        # ?windowS= trailing window in seconds
                        return self._respond(
                            ctrl.history.query_from_qs(url.query)
                        )
                    if parts == ["debug", "slo"]:
                        return self._respond(collect_slo(ctrl))
                    if parts == ["dashboard", "slo"]:
                        return self._respond_html(
                            dashboard.render_slo(ctrl, collect_slo(ctrl))
                        )
                    if parts == ["debug", "flightrec"]:
                        return self._respond(ctrl.flightrec.snapshot())
                    if parts == ["debug", "audit"]:
                        # cross-replica CRC sweep rollup (CrcAuditManager)
                        return self._respond(ctrl.crc_audit.snapshot())
                    if parts == ["debug", "deepstore"]:
                        # deep-store scrub/repair rollup + evidence rows
                        return self._respond(ctrl.deepstore_scrubber.snapshot())
                    if parts == ["debug", "stabilizer"]:
                        return self._respond(ctrl.stabilizer.debug_snapshot())
                    if len(parts) == 3 and parts[0] == "instances" and parts[2] == "drain":
                        # poll surface for the rolling-restart loop
                        try:
                            return self._respond(ctrl.drain_status(parts[1]))
                        except KeyError as e:
                            return self._respond({"error": str(e)}, 404)
                    if parts == ["dashboard", "metrics"]:
                        return self._respond_html(
                            dashboard.render_metrics(ctrl, collect_cluster_metrics(ctrl))
                        )
                    if parts == ["clusterstate"]:
                        qs = parse_qs(url.query)
                        if_newer = int((qs.get("ifNewer") or ["-1"])[0])
                        epoch = (qs.get("epoch") or [""])[0]
                        # "unchanged" only within the SAME controller
                        # incarnation: a restarted controller's version
                        # counter restarts, so a broker comparing its
                        # old (higher) version would otherwise freeze
                        # its routing forever
                        if (
                            epoch == ctrl.gateway.epoch
                            and ctrl.resources.version <= if_newer
                        ):
                            return self._respond(
                                {
                                    "version": ctrl.resources.version,
                                    "epoch": ctrl.gateway.epoch,
                                    "unchanged": True,
                                }
                            )
                        return self._respond(ctrl.gateway.cluster_state())
                    if len(parts) == 3 and parts[0] == "instances" and parts[2] == "messages":
                        return self._respond({"messages": ctrl.gateway.messages(parts[1])})
                    if (
                        len(parts) == 4
                        and parts[0] == "segments"
                        and parts[3] == "file"
                    ):
                        # raw segment download: GET /segments/{table}/{seg}/file
                        # (the download-URL-in-ZK-metadata analog)
                        import os

                        from pinot_tpu.segment.format import SEGMENT_FILE_NAME

                        path = os.path.join(
                            ctrl.store.segment_dir(parts[1], parts[2]), SEGMENT_FILE_NAME
                        )
                        if not os.path.exists(path):
                            return self._respond({"error": "not found"}, 404)
                        with open(path, "rb") as f:
                            return self._respond_bytes(f.read())
                    if parts == ["brokers"]:
                        return self._respond(
                            {"brokers": _alive_broker_urls(ctrl.resources)}
                        )
                    if parts == ["tables"]:
                        return self._respond({"tables": ctrl.resources.tables()})
                    if parts == ["tenants"]:
                        return self._respond({"tenants": ctrl.resources.list_tenants()})
                    if len(parts) == 2 and parts[0] == "tenants":
                        return self._respond(
                            {
                                "tenant": parts[1],
                                "ServerInstances": ctrl.resources.tenant_instances(parts[1], "server"),
                                "BrokerInstances": ctrl.resources.tenant_instances(parts[1], "broker"),
                            }
                        )
                    if len(parts) == 3 and parts[0] == "tables" and parts[2] == "size":
                        return self._respond(
                            {
                                "table": parts[1],
                                "reportedSizeInBytes": ctrl.store.table_size_bytes(parts[1]),
                            }
                        )
                    if len(parts) == 2 and parts[0] == "schemas":
                        schema = ctrl.resources.get_schema(parts[1])
                        if schema is None:
                            return self._respond({"error": "not found"}, 404)
                        return self._respond(schema.to_json())
                    if len(parts) == 3 and parts[0] == "tables" and parts[2] == "segments":
                        return self._respond(
                            {"segments": ctrl.resources.segments_of(parts[1])}
                        )
                    if len(parts) == 3 and parts[0] == "tables" and parts[2] == "idealstate":
                        return self._respond(ctrl.resources.get_ideal_state(parts[1]))
                    if len(parts) == 3 and parts[0] == "tables" and parts[2] == "externalview":
                        return self._respond(ctrl.resources.get_external_view(parts[1]))
                    return self._respond({"error": "not found"}, 404)
                except Exception as e:
                    return self._respond({"error": str(e)}, 500)

            def do_POST(self):
                url = urlparse(self.path)
                parts = _split_path(url.path)
                if parts is None:
                    return self._respond({"error": "bad path"}, 400)
                try:
                    if parts == ["pql"]:
                        body = self._read_json()
                        return self._respond(
                            _proxy_pql(
                                ctrl, body.get("pql", ""), bool(body.get("trace"))
                            )
                        )
                    if parts == ["instances"]:
                        return self._respond(ctrl.gateway.register(self._read_json()))
                    if parts == ["deepstore", "suspect"]:
                        # networked fetch-path feedback: a server's
                        # download failed CRC against the store copy
                        body = self._read_json()
                        ctrl.deepstore_scrubber.report_suspect(
                            str(body.get("table", "")),
                            str(body.get("segment", "")),
                            str(body.get("source", "")),
                        )
                        return self._respond({"status": "reported"})
                    if len(parts) == 3 and parts[0] == "instances" and parts[2] == "heartbeat":
                        # readiness (warming flag) rides the beat body
                        return self._respond(
                            ctrl.gateway.heartbeat(parts[1], self._read_json())
                        )
                    if len(parts) == 3 and parts[0] == "instances" and parts[2] == "ack":
                        return self._respond(ctrl.gateway.ack(parts[1], self._read_json()))
                    if len(parts) == 3 and parts[0] == "instances" and parts[2] in (
                        "drain", "undrain"
                    ):
                        fn = (
                            ctrl.drain_instance
                            if parts[2] == "drain"
                            else ctrl.undrain_instance
                        )
                        try:
                            return self._respond(fn(parts[1]))
                        except KeyError as e:
                            # same contract as the GET poll surface: an
                            # unknown name is 404, never a silent no-op
                            return self._respond({"error": str(e)}, 404)
                    if parts == ["schemas"]:
                        schema = Schema.from_json(self._read_json())
                        ctrl.add_schema(schema)
                        return self._respond({"status": "ok", "schema": schema.schema_name})
                    if parts == ["tables"]:
                        config = TableConfig.from_json(self._read_json())
                        if config.table_type == "REALTIME":
                            from pinot_tpu.realtime.stream import (
                                stream_provider_from_config,
                            )

                            if config.stream is None:
                                return self._respond(
                                    {"error": "REALTIME table needs streamConfigs"}, 400
                                )
                            provider = stream_provider_from_config(config.stream)
                            physical = ctrl.add_realtime_table(config, provider)
                        else:
                            physical = ctrl.add_table(config)
                        return self._respond({"status": "ok", "table": physical})
                    if parts == ["realtime", "consumed"]:
                        # LLC completion protocol: segmentConsumed
                        # (SegmentCompletionProtocol responses); the
                        # caller's lease epoch rides the payload and is
                        # fence-checked (typed 409 on mismatch)
                        body = self._read_json()
                        resp, target = ctrl.realtime_manager.completion.segment_consumed(
                            body["segment"], body["server"], int(body["offset"]),
                            epoch=body.get("epoch"),
                        )
                        return self._respond(
                            {"response": resp, "targetOffset": target}
                        )
                    if len(parts) == 4 and parts[:2] == ["realtime", "commit"]:
                        # committer upload: POST /realtime/commit/{segment}/{server}
                        # body = segment file bytes (segmentCommit);
                        # ?epoch= carries the committer's lease epoch
                        import tempfile

                        from pinot_tpu.segment.format import (
                            SEGMENT_FILE_NAME,
                            read_segment,
                        )

                        qs = parse_qs(url.query)
                        epoch = (qs.get("epoch") or [None])[0]
                        n = int(self.headers.get("Content-Length", "0"))
                        completion = ctrl.realtime_manager.completion
                        # fence BEFORE buffering/parsing the upload: a
                        # fenced-off committer (stale epoch -> typed
                        # 409, expired lease -> NOT_LEADER) retrying in
                        # a storm must not cost O(segment bytes) per
                        # rejection.  The body is still drained so the
                        # client reads the verdict instead of hitting a
                        # connection reset mid-send.
                        try:
                            fenced = completion.commit_fence_check(
                                parts[2], parts[3], epoch=epoch
                            )
                        except StaleEpochError:
                            self.rfile.read(n)
                            raise
                        if fenced is not None:
                            self.rfile.read(n)
                            return self._respond({"response": fenced})
                        data = self.rfile.read(n)
                        with tempfile.TemporaryDirectory() as td:
                            with open(os.path.join(td, SEGMENT_FILE_NAME), "wb") as f:
                                f.write(data)
                            committed = read_segment(td)
                        resp = completion.segment_commit(
                            parts[2], parts[3], committed, epoch=epoch
                        )
                        return self._respond({"response": resp})
                    if parts == ["tenants"]:
                        body = self._read_json()
                        tagged = ctrl.resources.create_tenant(
                            body["name"], body.get("role", "server"), int(body.get("count", 1))
                        )
                        return self._respond({"status": "ok", "instances": tagged})
                    if len(parts) == 3 and parts[0] == "tables" and parts[2] == "quota":
                        # live quota update/removal: bumps the cluster-
                        # state version so running brokers (in-process
                        # AND networked) converge on the new rate —
                        # {"maxQueriesPerSecond": null} removes the quota
                        body = self._read_json()
                        try:
                            ctrl.resources.update_table_quota(
                                parts[1],
                                body.get("maxQueriesPerSecond"),
                                body.get("burstQueries"),
                            )
                        except KeyError as e:
                            return self._respond({"error": str(e)}, 404)
                        return self._respond({"status": "ok", "table": parts[1]})
                    if len(parts) == 3 and parts[0] == "tables" and parts[2] == "rebalance":
                        qs = parse_qs(url.query)
                        dry = (qs.get("dryRun") or ["false"])[0].lower() == "true"
                        return self._respond(ctrl.rebalance_table(parts[1], dry_run=dry))
                    if len(parts) == 2 and parts[0] == "segments":
                        # binary segment upload: POST /segments/{table}
                        # (PinotSegmentUploadRestletResource analog);
                        # ?server= pins assignment (HLC server-owned)
                        n = int(self.headers.get("Content-Length", "0"))
                        body = self.rfile.read(n)
                        qs = parse_qs(url.query)
                        pin = qs.get("server")
                        servers = ctrl.upload_segment_bytes(parts[1], body, servers=pin)
                        return self._respond({"status": "ok", "servers": servers})
                    if parts == ["realtime", "hlc", "roll"]:
                        body = self._read_json()
                        seg = ctrl.realtime_manager.register_hlc_roll(
                            body["table"], body["server"],
                            int(body["idx"]), int(body["seq"]),
                        )
                        return self._respond({"status": "ok", "segment": seg})
                    return self._respond({"error": "not found"}, 404)
                except StaleEpochError as e:
                    return self._respond_stale(e)
                except Exception as e:
                    logger.warning("REST handler error", exc_info=True)
                    return self._respond({"error": str(e)}, 400)

            def do_DELETE(self):
                url = urlparse(self.path)
                parts = _split_path(url.path)
                if parts is None:
                    return self._respond({"error": "bad path"}, 400)
                try:
                    if len(parts) == 2 and parts[0] == "tables":
                        ctrl.delete_table(parts[1])
                        return self._respond({"status": "ok"})
                    if len(parts) == 4 and parts[0] == "tables" and parts[2] == "segments":
                        ctrl.delete_segment(parts[1], parts[3])
                        return self._respond({"status": "ok"})
                    return self._respond({"error": "not found"}, 404)
                except StaleEpochError as e:
                    # same typed 409 as do_POST: deletes hit the fenced
                    # property-store path too on a zombie controller
                    return self._respond_stale(e)
                except Exception as e:
                    logger.warning("REST handler error", exc_info=True)
                    return self._respond({"error": str(e)}, 400)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._controller = controller
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._controller.gateway.start()
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._controller.gateway.stop()
        self._httpd.shutdown()
        self._httpd.server_close()
