"""Networked control plane: the ZooKeeper/Helix transport analog.

The reference cluster runs controller, brokers, and servers as separate
processes coordinated through ZooKeeper: the controller writes ideal
state, Helix delivers transition *messages* to participant servers,
servers execute them and write their *current state*, and brokers watch
external views to rebuild routing (``HelixServerStarter.java:63``,
``HelixBrokerStarter.java:57``, ``HelixExternalViewBasedRouting.java:65``).

This module provides the same split over plain HTTP, with the
controller playing ZooKeeper's role as the rendezvous point:

- ``MessageBoard`` — per-instance queues of transition messages (the
  Helix message paths in ZK).
- ``RemoteParticipant`` — the controller-side stub for a server living
  in another process: enqueues messages and returns "pending"; the
  server reports resulting state via ``ClusterResourceManager.
  report_state`` (the CurrentState write).
- ``ParticipantGateway`` — registration, heartbeat-based liveness (the
  ZK-session-timeout analog), message fetch/ack, and a versioned
  cluster-state snapshot that remote brokers poll (the watch analog).

Endpoints are mounted on ``ControllerHttpServer``; the wire format is
JSON everywhere except segment downloads (raw bytes).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.controller.resource_manager import (
    CONSUMING,
    ClusterResourceManager,
    DROPPED,
    ERROR,
    InstanceState,
    OFFLINE,
    ONLINE,
    Participant,
)

logger = logging.getLogger(__name__)


class MessageBoard:
    """Per-instance FIFO of transition messages awaiting pickup.

    At-least-once delivery, as Helix messages in ZK: ``fetch`` peeks
    (the message stays queued until the server acks it by id), so a
    response lost on the wire is simply redelivered on the next poll.
    Transitions are idempotent on the server side (CRC-skip load,
    idempotent remove), which makes redelivery safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queues: Dict[str, List[Dict[str, Any]]] = {}
        self._next_id = 0

    def post(self, instance: str, msg: Dict[str, Any]) -> int:
        with self._lock:
            self._next_id += 1
            msg = dict(msg, msgId=self._next_id)
            self._queues.setdefault(instance, []).append(msg)
            return self._next_id

    def fetch(self, instance: str) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._queues.get(instance, []))

    def remove(self, instance: str, msg_id: Optional[int]) -> None:
        if msg_id is None:
            return
        with self._lock:
            q = self._queues.get(instance)
            if q is not None:
                self._queues[instance] = [m for m in q if m["msgId"] != msg_id]

    def clear(self, instance: str) -> None:
        with self._lock:
            self._queues.pop(instance, None)


class RemoteParticipant(Participant):
    """Controller-side stub for a server process reachable over HTTP.

    Transition requests become queued messages; the participant answers
    "pending" (None) and the server's ack later lands in
    ``report_state``. CONSUMING is refused for now: networked realtime
    consumption needs the stream config shipped to the server, which the
    in-process deployment covers (see realtime/llc.py).
    """

    def __init__(self, name: str, board: MessageBoard) -> None:
        super().__init__(name, self._enqueue)
        self.board = board

    def _enqueue(
        self, table: str, segment: str, target: str, info: Dict[str, Any]
    ) -> Optional[bool]:
        meta = info.get("metadata")
        msg: Dict[str, Any] = {
            "type": "transition",
            "table": table,
            "segment": segment,
            "target": target,
            "crc": getattr(meta, "crc", None),
        }
        # external download URIs (hdfs://, blob-store http…) ride to the
        # server for scheme-dispatched fetching; file:// points at the
        # controller's own disk, so remote servers keep the
        # controller-served HTTP download instead
        uri = info.get("downloadUri")
        if uri and not uri.startswith("file://"):
            msg["downloadUri"] = uri
        if info.get("invertedIndexColumns"):
            msg["invertedIndexColumns"] = list(info["invertedIndexColumns"])
        if info.get("schema") is not None:
            # schema rides as JSON so the remote server can inject
            # default columns for schema-evolved segments at load
            msg["schemaJson"] = info["schema"].to_json()
        if target == CONSUMING:
            # ship the full consume spec so the remote process can run
            # the consumer + LLC completion protocol on its own
            # (LLRealtimeSegmentDataManager.java:68 does the same with
            # the stream config from ZK segment metadata)
            desc = info.get("streamDescriptor")
            if desc is None:
                logger.warning(
                    "remote participant %s cannot host CONSUMING %s/%s: "
                    "stream is not network-describable",
                    self.name, table, segment,
                )
                return False
            msg.update(
                {
                    "streamDescriptor": desc,
                    "partition": info.get("partition", 0),
                    "startOffset": info.get("startOffset", 0),
                    "rowsPerSegment": info.get("rowsPerSegment", 100_000),
                    "schemaJson": info.get("schemaJson"),
                    "consumerType": info.get("consumerType", "lowlevel"),
                }
            )
        self.board.post(self.name, msg)
        return None


class ParticipantGateway:
    """Controller-side state for remote instances: registration,
    heartbeats, liveness, messages, and broker-facing cluster state."""

    def __init__(
        self,
        resources: ClusterResourceManager,
        heartbeat_timeout_s: float = 6.0,
        check_interval_s: float = 1.0,
        metrics=None,
        flap_window_s: float = 60.0,
        flap_threshold: int = 3,
        flap_hold_base_s: float = 5.0,
        flap_hold_max_s: float = 300.0,
        clock=None,
        epoch: Optional[int] = None,
        lease_s: Optional[float] = None,
        fault_injector=None,
    ) -> None:
        from pinot_tpu.common.fencing import default_lease_s

        self.resources = resources
        self.board = MessageBoard()
        # optional ControllerMetrics: control-plane traffic counters
        self.metrics = metrics
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self._check_interval_s = check_interval_s
        self._heartbeats: Dict[str, float] = {}
        # serving leases (common/fencing.py): every heartbeat reply
        # grants write authority for lease_s; the stabilizer will not
        # move a dead server's replicas before its lease window closes
        self.lease_s = lease_s if lease_s is not None else default_lease_s()
        # link-level chaos hook (common/faults.py NetworkFaultInjector):
        # instance-named control-plane calls consult it at the
        # controller edge, so a cut server->controller link drops
        # heartbeats even when the client was not injector-wired
        self.fault_injector = fault_injector
        # flap hysteresis: dead->alive cycles inside flap_window_s; at
        # flap_threshold the re-admit is HELD for an escalating window
        # (doubling per extra flap, capped) so the stabilizer never
        # thrashes segments onto a host that keeps dying
        self.flap_window_s = flap_window_s
        self.flap_threshold = flap_threshold
        self.flap_hold_base_s = flap_hold_base_s
        self.flap_hold_max_s = flap_hold_max_s
        self._clock = clock or time.monotonic
        self._revives: Dict[str, List[float]] = {}  # dead->alive times
        self._readmit_hold: Dict[str, float] = {}  # name -> held until
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # deferred-repair callback, wired by the Controller to the
        # realtime manager's ensure_consuming_segments
        self.on_server_available = None
        # incarnation id: cluster-state versions are only comparable
        # within one controller process lifetime (see /clusterstate).
        # Wired from the Controller this is the PERSISTED integer
        # fencing epoch (property store cluster/epoch) — the cluster-
        # wide write-fencing token; standalone gateways fall back to a
        # process-unique string (snapshot identity only, fence unarmed).
        if epoch is not None:
            self.epoch = str(int(epoch))
        else:
            self.epoch = f"{os.getpid()}-{time.monotonic_ns()}"
        # versioned snapshot cache (fleet breadth): building the full
        # cluster state walks every table's external view + segment
        # metadata (time boundaries), so at 100+ tables x N brokers
        # polling, an unchanged cluster must serve ONE build per
        # version, not one per poll.  Keyed on the resource version the
        # build captured; any bump (view change, registration, drain)
        # naturally invalidates it.
        self._state_cache: Optional[Dict[str, Any]] = None
        self._state_cache_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._monitor_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def _monitor_loop(self) -> None:
        while not self._stop.wait(self._check_interval_s):
            now = time.monotonic()
            with self._lock:
                expired = [
                    name
                    for name, ts in self._heartbeats.items()
                    if now - ts > self.heartbeat_timeout_s
                ]
            for name in expired:
                inst = self.resources.instances.get(name)
                if inst is not None and inst.alive:
                    logger.warning("instance %s missed heartbeats; marking dead", name)
                    if self.metrics is not None:
                        self.metrics.meter("instancesMarkedDead").mark()
                    self.board.clear(name)
                    # one code path: this liveness flip rewrites external
                    # views (version bump -> remote brokers refetch) AND
                    # fires instance listeners (in-process broker health
                    # trackers force the circuit open) — no separate
                    # health poll that could race the routing update
                    self.resources.set_instance_alive(name, False)

    # -- flap hysteresis ----------------------------------------------
    def _flap_gate(self, name: str) -> Optional[float]:
        """Called when a DEAD instance asks to be re-admitted.  Returns
        the seconds remaining on a re-admit hold (refuse), or None
        (admit now).  Only ADMITTED dead->alive cycles count as flaps,
        so a stable survivor is never punished for heartbeating through
        its own hold window."""
        now = self._clock()
        with self._lock:
            hold_until = self._readmit_hold.get(name, 0.0)
            if now < hold_until:
                return hold_until - now
            revives = [
                t
                for t in self._revives.get(name, ())
                if now - t < self.flap_window_s
            ]
            if len(revives) >= self.flap_threshold:
                excess = len(revives) - self.flap_threshold
                hold = min(
                    self.flap_hold_base_s * (2**excess), self.flap_hold_max_s
                )
                self._readmit_hold[name] = now + hold
                # the refused attempt itself counts into the window (one
                # entry per hold — heartbeats DURING a hold return above
                # without appending), so repeated holds escalate; once
                # holds outgrow the window the entries age out and a
                # now-stable host is re-admitted
                revives.append(now)
                self._revives[name] = revives
                logger.warning(
                    "instance %s flapped %d times in %.0fs; holding re-admit "
                    "for %.1fs",
                    name, len(revives) - 1, self.flap_window_s, hold,
                )
                return hold
            revives.append(now)
            self._revives[name] = revives
            flapped = len(revives) > 1
        if flapped and self.metrics is not None:
            self.metrics.meter("gateway.flaps").mark()
        return None

    # -- leases --------------------------------------------------------
    def _grant_lease(self, name: str) -> Dict[str, Any]:
        """Record + serialize a serving lease for one instance.  The
        lease rides every heartbeat/registration reply; its epoch is the
        controller's fencing incarnation, so a commit sent under an old
        controller's lease is typed-rejected after a failover."""
        now = self._clock()
        inst = self.resources.instances.get(name)
        if inst is not None:
            inst.lease_until = now + self.lease_s
        if self.metrics is not None:
            self.metrics.meter("lease.granted").mark()
        return {"epoch": self.fencing_epoch, "durationS": self.lease_s}

    def server_lease_valid(self, name: str) -> bool:
        """True while ``name`` holds an unexpired serving lease.  An
        instance that was never granted one (in-process participant, no
        heartbeats) keeps implicit authority — the fence only arms once
        leases are being issued for it."""
        inst = self.resources.instances.get(name)
        if inst is None or inst.lease_until is None:
            return inst is not None
        return self._clock() < inst.lease_until

    @property
    def fencing_epoch(self) -> int:
        # derived from the string epoch when it is an integer
        # incarnation (Controller-wired); -1 disarms the fence
        from pinot_tpu.common.fencing import epoch_int

        return epoch_int(self.epoch)

    def _linked(self, src: str, fn):
        """Route one instance-named control-plane call through the link
        injector (no-op without one).  This is the CONTROLLER-EDGE
        hook, for harnesses that cannot wire the client processes; an
        in-process harness that injector-wires its clients must NOT
        also wire the gateway, or faults double-apply on these links."""
        from pinot_tpu.common.faults import call_on_controller_link

        return call_on_controller_link(
            self.fault_injector, src, fn, metrics=self.metrics
        )

    # -- instance API (called from HTTP handlers) ----------------------
    def register(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        return self._linked(payload["name"], lambda: self._register(payload))

    def _register(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        name = payload["name"]
        role = payload.get("role", "server")
        if self.metrics is not None:
            self.metrics.meter("instanceRegistrations").mark()
        prev = self.resources.instances.get(name)
        was_dead = prev is not None and not prev.alive
        # a crash-looping process re-REGISTERS on every loop: the same
        # hysteresis that gates heartbeat revives gates registration, or
        # the hold would be trivially bypassed
        hold = self._flap_gate(name) if was_dead else None
        if payload.get("tags"):
            tags = set(payload["tags"])
        else:
            # tenant tags are controller-assigned state (create_tenant):
            # a restarting instance that doesn't announce tags must keep
            # the ones it had, not fall back to DefaultTenant
            prev = self.resources.instances.get(name)
            tags = set(prev.tags) if prev is not None else {"DefaultTenant"}
        state = InstanceState(
            name,
            role=role,
            url=payload.get("url"),
            addr=tuple(payload["addr"]) if payload.get("addr") else None,
            tags=tags,
        )
        participant = RemoteParticipant(name, self.board) if role == "server" else None
        with self._lock:
            self._heartbeats[name] = time.monotonic()
        self.resources.register_instance(state, participant)
        if hold is not None:
            # flapping host: registered (address/participant current)
            # but NOT re-admitted to routing until the hold expires —
            # its heartbeats will revive it once the gate clears
            self.resources.set_instance_alive(name, False)
            return {
                "status": "held",
                "holdSeconds": round(hold, 3),
                "heartbeatTimeoutSeconds": self.heartbeat_timeout_s,
            }
        if role == "server":
            # replay any ideal-state transitions targeting this server:
            # covers re-registration after a server crash AND first
            # registration with a *recovered* controller whose ideal
            # states came from the property store (the fresh
            # InstanceState is already alive, so set_instance_alive
            # would no-op; a truly new server replays nothing)
            self.resources.reconcile_instance(name)
            self._kick_server_available()
        return {
            "status": "ok",
            "heartbeatTimeoutSeconds": self.heartbeat_timeout_s,
            "draining": state.draining,
            "lease": self._grant_lease(name),
        }

    def heartbeat(
        self, name: str, payload: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        return self._linked(name, lambda: self._heartbeat(name, payload))

    def _heartbeat(
        self, name: str, payload: Optional[Dict[str, Any]] = None
    ) -> Dict[str, Any]:
        if self.metrics is not None:
            self.metrics.meter("heartbeats").mark()
        inst = self.resources.instances.get(name)
        if inst is None:
            return {"error": "unknown instance", "reregister": True}
        with self._lock:
            self._heartbeats[name] = time.monotonic()
        # warm-start readiness rides the liveness beat (absent key =
        # legacy heartbeat, leave the flag alone so a plain {} body
        # cannot clear a warming state it knows nothing about)
        if payload is not None and "warming" in payload:
            self.resources.set_instance_warming(name, bool(payload["warming"]))
        if not inst.alive:
            hold = self._flap_gate(name)
            if hold is not None:
                # flapping: stays out of routing until the hold expires
                # (the heartbeat is still recorded so the monitor loop
                # doesn't pile a fresh death on top) — and NO lease: a
                # held instance has no write authority either
                return {
                    "status": "held",
                    "holdSeconds": round(hold, 3),
                    "draining": inst.draining,
                }
            self.resources.set_instance_alive(name, True)
            self._kick_server_available()
        # drain ack rides the heartbeat reply: a draining server learns
        # its state without a dedicated poll and surfaces it in status()
        return {"status": "ok", "draining": inst.draining, "lease": self._grant_lease(name)}

    def _kick_server_available(self) -> None:
        """A server just became available: run deferred repairs (e.g.
        recreate missing CONSUMING segments whose creation failed while
        no replica was registered) without waiting for the periodic
        ValidationManager tick."""
        cb = self.on_server_available
        if cb is None:
            return

        def run():
            try:
                cb()
            except Exception:
                logger.warning("server-available repair failed", exc_info=True)

        threading.Thread(target=run, daemon=True).start()

    def messages(self, name: str) -> List[Dict[str, Any]]:
        return self._linked(name, lambda: self.board.fetch(name))

    def ack(self, name: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        return self._linked(name, lambda: self._ack(name, payload))

    def _ack(self, name: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        if self.metrics is not None:
            self.metrics.meter("transitionAcks").mark()
        self.board.remove(name, payload.get("msgId"))
        state = payload["state"] if payload.get("ok", True) else ERROR
        self.resources.report_state(
            name, payload["table"], payload["segment"], state
        )
        return {"status": "ok"}

    # -- broker API ----------------------------------------------------
    def cluster_state(self) -> Dict[str, Any]:
        """Versioned snapshot remote brokers poll to rebuild routing,
        server addresses, quotas, and hybrid time boundaries.  Built at
        most once per resource version: concurrent brokers polling an
        unchanged cluster share the cached document (the O(tables)
        walk happens on change, not per poll)."""
        if self.metrics is not None:
            self.metrics.meter("clusterStatePolls").mark()
        res = self.resources
        with self._state_cache_lock:
            cached = self._state_cache
        if cached is not None and cached["version"] == res.version:
            if self.metrics is not None:
                self.metrics.meter("clusterStateCacheHits").mark()
            return cached
        built = self._build_cluster_state()
        with self._state_cache_lock:
            self._state_cache = built
        return built

    def _build_cluster_state(self) -> Dict[str, Any]:
        res = self.resources
        with res._lock:
            # version captured BEFORE the snapshot: a concurrent bump then
            # makes the broker refetch (at-least-once), never miss forever
            version = res.version
            instances = dict(res.instances)
            configs = dict(res.table_configs)
        out_epoch = self.epoch
        tables: Dict[str, Any] = {}
        boundaries: Dict[str, Any] = {}
        quotas: Dict[str, Any] = {}
        for table in res.tables():
            view = res.get_external_view(table)
            # hide dead AND draining servers from routing, as
            # _notify_view does: brokers stop sending NEW queries to a
            # draining instance while its in-flight ones finish
            tables[table] = {
                seg: {
                    srv: st
                    for srv, st in replicas.items()
                    if instances.get(srv) is not None
                    and instances[srv].alive
                    and not instances[srv].draining
                }
                for seg, replicas in view.items()
            }
            config = configs.get(table)
            if config is not None:
                quotas[table] = {
                    "rawName": config.raw_name,
                    "maxQueriesPerSecond": config.quota.max_queries_per_second,
                    "burstQueries": config.quota.burst_queries,
                    # per-table SLO objectives propagate with the quota
                    # (broker/network_starter applies them per poll)
                    "slo": config.slo.to_json() if config.slo is not None else None,
                    # declared key partitioning feeds the remote broker's
                    # join planner (colocated strategy eligibility)
                    "partitioning": (
                        config.partitioning.to_json()
                        if config.partitioning is not None
                        else None
                    ),
                }
            if table.endswith("_OFFLINE"):
                from pinot_tpu.broker.time_boundary import compute_boundary

                metas = []
                for seg in res.segments_of(table):
                    info = res.get_segment_metadata(table, seg)
                    if info and info.get("metadata") is not None:
                        metas.append(info["metadata"])
                boundary = compute_boundary(metas)
                if boundary is not None:
                    boundaries[table] = list(boundary)
        servers = {
            name: list(inst.addr)
            for name, inst in instances.items()
            if inst.role == "server" and inst.alive and inst.addr is not None
        }
        # declared-dead servers ride the same versioned snapshot that
        # carries the routing rebuild, so a remote broker's health
        # tracker and routing table update from ONE event, atomically
        dead_servers = [
            name
            for name, inst in instances.items()
            if inst.role == "server" and not inst.alive
        ]
        # draining servers stay in "servers" (their addresses must keep
        # resolving for in-flight work) but are listed here so remote
        # brokers/ops can tell deliberate drain from failure
        draining_servers = [
            name
            for name, inst in instances.items()
            if inst.role == "server" and inst.alive and inst.draining
        ]
        # warming servers stay fully routable; remote brokers just
        # prefer a ready replica until the prewarm pass completes
        warming_servers = [
            name
            for name, inst in instances.items()
            if inst.role == "server" and inst.alive and inst.warming
        ]
        return {
            "version": version,
            "epoch": out_epoch,
            "tables": tables,
            "servers": servers,
            "deadServers": dead_servers,
            "drainingServers": draining_servers,
            "warmingServers": warming_servers,
            "quotas": quotas,
            "timeBoundaries": boundaries,
        }
