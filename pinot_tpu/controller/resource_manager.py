"""Cluster resource manager: the ideal-state / external-view brain.

The Helix-semantics core the reference builds on
(``PinotHelixResourceManager.java:103``,
``PinotTableIdealStateBuilder.java``), re-implemented as an explicit
state machine:

- **ideal state** per table: ``{segment -> {server -> target_state}}``
  — what the controller wants (N replicas per segment, balanced
  round-robin assignment).
- **external view** per table: ``{segment -> {server -> actual_state}}``
  — what participants report after executing transitions.
- **participants**: registered server callbacks executing
  OFFLINE->ONLINE / ONLINE->OFFLINE / ->DROPPED transitions (the
  SegmentOnlineOfflineStateModelFactory analog,
  ``SegmentOnlineOfflineStateModelFactory.java:85``).
- **listeners**: broker callbacks receiving external-view updates to
  rebuild routing (``HelixExternalViewBasedRouting.java:65``).

Everything is synchronous + in-process here; the transport seam is the
participant/listener callback interface, so a networked deployment
swaps callbacks for RPC without touching the state logic.
"""
from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from pinot_tpu.common.schema import Schema
from pinot_tpu.common.tableconfig import TableConfig
from pinot_tpu.segment.immutable import SegmentMetadata

logger = logging.getLogger(__name__)

ONLINE = "ONLINE"
OFFLINE = "OFFLINE"
CONSUMING = "CONSUMING"
DROPPED = "DROPPED"
ERROR = "ERROR"


@dataclass
class InstanceState:
    name: str
    role: str  # "server" | "broker"
    alive: bool = True
    tags: Set[str] = field(default_factory=lambda: {"DefaultTenant"})
    url: Optional[str] = None  # broker HTTP url (client discovery)
    addr: Optional[Tuple[str, int]] = None  # server query-TCP endpoint
    # drain/decommission: a draining server keeps serving in-flight
    # queries but is hidden from NEW routing covers and excluded from
    # segment placement; the SelfStabilizer migrates its replicas off
    # so a rolling restart is drain -> restart -> rejoin (undrain)
    draining: bool = False
    # warm-start readiness (heartbeat-reported): True while the server
    # is still prewarming its compile working set.  A warming server
    # serves normally — brokers merely deprioritize it and the
    # stabilizer defers trimming the replica it is replacing.
    warming: bool = False
    # serving-lease expiry (monotonic deadline, ParticipantGateway
    # clock): None = never leased (in-process participant — implicit
    # authority, and the stabilizer applies only its grace window).
    # While ``now < lease_until`` a heartbeat-missing server may STILL
    # be alive-but-partitioned and serving from its last snapshot, so
    # the stabilizer must not move its replicas yet (lease fence).
    lease_until: Optional[float] = None


class Participant:
    """Server-side transition executor registered with the controller.

    ``on_transition`` returns True (done), False (failed -> ERROR), or
    None (pending — a remote participant queued the work and will report
    the resulting state later via ``report_state``, the Helix
    message+current-state split)."""

    def __init__(
        self,
        name: str,
        on_transition: Callable[[str, str, str, Dict[str, Any]], Optional[bool]],
    ) -> None:
        self.name = name
        # on_transition(table, segment, target_state, metadata) -> ok|None
        self.on_transition = on_transition


class ClusterResourceManager:
    def __init__(self, property_store=None) -> None:
        """``property_store`` (controller.property_store.PropertyStore)
        makes schemas, table configs, ideal states, and segment
        metadata — incl. LLC offset checkpoints — durable across
        controller restarts (the ZK property-store role,
        ``PinotHelixResourceManager.java:103``).  None keeps everything
        in memory (embedded/test deployments)."""
        self.property_store = property_store
        self._lock = threading.RLock()
        self.schemas: Dict[str, Schema] = {}
        self.table_configs: Dict[str, TableConfig] = {}
        self.segment_metadata: Dict[Tuple[str, str], Dict[str, Any]] = {}  # (table, seg) -> zk-like record
        self.ideal_states: Dict[str, Dict[str, Dict[str, str]]] = {}
        self.external_views: Dict[str, Dict[str, Dict[str, str]]] = {}
        self.instances: Dict[str, InstanceState] = {}
        self._participants: Dict[str, Participant] = {}
        # drain flags survive BOTH instance re-registration and
        # controller restarts: kept by name (not on the InstanceState,
        # which registration replaces) and persisted to the property
        # store so a recovered controller resumes an in-flight drain
        self._draining_flags: Set[str] = set()
        if property_store is not None:
            for name in property_store.list_keys("instances"):
                rec = property_store.get("instances", name)
                if rec and rec.get("draining"):
                    self._draining_flags.add(name)
        self._view_listeners: List[Callable[[str, Dict[str, Dict[str, str]]], None]] = []
        self._instance_listeners: List[Callable[[str, bool], None]] = []
        # deep-store suspect intake: the controller points this at its
        # DeepStoreScrubber.report_suspect so in-process servers can
        # flag a store copy whose bytes failed CRC on fetch
        # (table, segment, source_uri) -> None; None = no scrubber
        self.report_store_suspect: Optional[Callable[[str, str, str], None]] = None
        self._assign_rr = 0
        # monotonically bumped on every view/instance change; remote
        # brokers poll it to decide when to rebuild routing
        self.version = 0

    def bump_version(self) -> int:
        with self._lock:
            self.version += 1
            return self.version

    # -- instances ----------------------------------------------------
    def instances_snapshot(self) -> List[InstanceState]:
        """Point-in-time instance copies for lock-free iteration by
        readers (dashboard pages, broker discovery). Tags are copied too
        so create_tenant can't mutate a set mid-iteration."""
        with self._lock:
            return [replace(i, tags=set(i.tags)) for i in self.instances.values()]

    def register_instance(self, state: InstanceState, participant: Optional[Participant] = None) -> None:
        with self._lock:
            # a drain is an operator intent keyed by NAME: registration
            # (fresh process or re-register after a controller restart)
            # must not silently re-admit a draining instance — only an
            # explicit undrain does
            state.draining = state.name in self._draining_flags
            self.instances[state.name] = state
            if participant is not None:
                self._participants[state.name] = participant
        self.bump_version()

    def set_instance_draining(self, name: str, draining: bool) -> None:
        """Mark an instance draining (decommission intent): it keeps
        answering in-flight queries but drops out of NEW routing covers
        and of segment placement; the SelfStabilizer migrates its
        replicas off.  The flag is durable (property store) and survives
        re-registration — cleared only by an explicit undrain."""
        with self._lock:
            inst = self.instances.get(name)
            if inst is None and name not in self._draining_flags:
                if not draining:
                    return
                raise KeyError(f"unknown instance {name!r}")
            if draining:
                self._draining_flags.add(name)
            else:
                self._draining_flags.discard(name)
            if inst is not None:
                if inst.draining == draining:
                    return
                inst.draining = draining
            tables = list(self.external_views.keys())
        if self.property_store is not None:
            if draining:
                self.property_store.put("instances", name, {"draining": True})
            else:
                self.property_store.delete("instances", name)
        # routing covers rebuild from the filtered views (draining
        # servers hidden), on the same version bump remote brokers poll
        for table in tables:
            self._notify_view(table)
        self.bump_version()

    def set_instance_warming(self, name: str, warming: bool) -> None:
        """Warm-start readiness flip (heartbeat-reported).  Routing
        covers are untouched — a warming server serves — but the
        version bump makes remote brokers refetch the cluster state
        (its ``warmingServers`` list feeds their deprioritization)."""
        with self._lock:
            inst = self.instances.get(name)
            if inst is None or inst.warming == warming:
                return
            inst.warming = warming
        self.bump_version()

    def is_instance_warming(self, name: str) -> bool:
        with self._lock:
            inst = self.instances.get(name)
            return inst is not None and inst.warming

    def segments_on(self, name: str) -> Dict[str, List[str]]:
        """Ideal-state replicas still placed on ``name`` per table (the
        drain endpoint's drained-vs-remaining accounting)."""
        with self._lock:
            return {
                table: segs
                for table, ideal in self.ideal_states.items()
                if (segs := sorted(s for s, r in ideal.items() if name in r))
            }

    def set_instance_alive(self, name: str, alive: bool) -> None:
        """Liveness flip (the ZK-session-loss analog): a dead server's
        partitions drop out of every external view and routing rebuilds."""
        tables: List[str]
        with self._lock:
            inst = self.instances.get(name)
            if inst is None or inst.alive == alive:
                return
            inst.alive = alive
            tables = list(self.external_views.keys())
        for table in tables:
            changed = False
            with self._lock:
                view = self.external_views.get(table, {})
                for seg, replicas in view.items():
                    if name in replicas:
                        replicas[name] = OFFLINE if not alive else replicas[name]
                        changed = True
            if changed or alive:
                self._notify_view(table)
        # the SAME liveness flip that rebuilt routing also reaches
        # broker health trackers (heartbeat-miss -> penalty box, and
        # recovery -> circuit closed) — one code path, no separate poll
        self._notify_instance(name, alive)
        if alive:
            self._reconcile_instance(name)

    def reconcile_instance(self, name: str) -> None:
        """Replay this instance's ideal-state transitions (used on
        participant re-registration, where the fresh InstanceState is
        already alive so set_instance_alive would no-op)."""
        self._reconcile_instance(name)

    def _reconcile_instance(self, name: str) -> None:
        """On instance (re)start: replay its ideal-state transitions."""
        with self._lock:
            tables = list(self.ideal_states.keys())
        for table in tables:
            with self._lock:
                ideal = dict(self.ideal_states.get(table, {}))
            for seg, replicas in ideal.items():
                if replicas.get(name) in (ONLINE, CONSUMING):
                    self._execute_transition(table, seg, name, replicas[name])
            self._notify_view(table)

    def reload_table(self, physical: str) -> None:
        """Re-execute every ONLINE transition for a table's current
        ideal state (the reference's segment-reload API,
        PinotSegmentRestletResource reload).  CRC-skip on the servers
        makes this metadata-cheap; it is how schema evolution reaches
        segments loaded before the schema grew."""
        with self._lock:
            ideal = dict(self.ideal_states.get(physical, {}))
        for seg, replicas in ideal.items():
            for server, state in replicas.items():
                if state == ONLINE:
                    self._execute_transition(physical, seg, server, ONLINE)
        self._notify_view(physical)

    def tables_of_schema(self, raw_name: str) -> List[str]:
        with self._lock:
            return [
                phys
                for phys, cfg in self.table_configs.items()
                if cfg.raw_name == raw_name
            ]

    # -- listeners ----------------------------------------------------
    def add_view_listener(self, fn: Callable[[str, Dict[str, Dict[str, str]]], None]) -> None:
        with self._lock:
            self._view_listeners.append(fn)

    def add_instance_listener(self, fn: Callable[[str, bool], None]) -> None:
        """Subscribe to instance-liveness flips (``(name, alive)``); the
        broker health tracker consumes these so a controller-declared
        dead server enters the penalty box immediately."""
        with self._lock:
            self._instance_listeners.append(fn)

    def _notify_instance(self, name: str, alive: bool) -> None:
        with self._lock:
            listeners = list(self._instance_listeners)
        for fn in listeners:
            try:
                fn(name, alive)
            except Exception:
                logger.exception("instance listener failed for %s", name)

    def _routable(self, srv: str) -> bool:
        """Server visible to brokers for NEW queries: registered, alive,
        and not draining (a draining server still answers in-flight
        work; it just stops receiving fresh covers)."""
        inst = self.instances.get(srv)
        return inst is not None and inst.alive and not inst.draining

    def _notify_view(self, table: str) -> None:
        self.bump_version()
        with self._lock:
            view = {
                seg: {
                    srv: st
                    for srv, st in replicas.items()
                    if self._routable(srv)
                }
                for seg, replicas in self.external_views.get(table, {}).items()
            }
            listeners = list(self._view_listeners)
        for fn in listeners:
            try:
                fn(table, view)
            except Exception:
                logger.exception("view listener failed for %s", table)

    # -- tenants ------------------------------------------------------
    def create_tenant(self, name: str, role: str, count: int) -> List[str]:
        """Tag ``count`` live, not-yet-dedicated instances of ``role``
        with the tenant tag (the PinotTenantRestletResource /
        tag-instances flow of the reference).  Returns tagged names."""
        with self._lock:
            free = sorted(
                i.name
                for i in self.instances.values()
                if i.role == role and i.alive and not (i.tags - {"DefaultTenant"})
            )
            if len(free) < count:
                raise RuntimeError(
                    f"tenant {name!r}: need {count} untagged {role}s, have {len(free)}"
                )
            tagged = free[:count]
            for n in tagged:
                # dedication: the tenant tag replaces DefaultTenant (the
                # reference untags the default when an instance joins a
                # tenant), so default-tenant tables stop landing here
                self.instances[n].tags.add(name)
                self.instances[n].tags.discard("DefaultTenant")
        self.bump_version()
        return tagged

    def list_tenants(self) -> Dict[str, List[str]]:
        """All tenant tags -> member instance names."""
        with self._lock:
            out: Dict[str, List[str]] = {}
            for inst in self.instances.values():
                for tag in inst.tags:
                    out.setdefault(tag, []).append(inst.name)
            return {t: sorted(ns) for t, ns in sorted(out.items())}

    def tenant_instances(self, name: str, role: Optional[str] = None) -> List[str]:
        with self._lock:
            return sorted(
                i.name
                for i in self.instances.values()
                if name in i.tags and (role is None or i.role == role)
            )

    def _live_tenant_members(self, tag: str, role: str) -> List[str]:
        with self._lock:
            return sorted(
                i.name
                for i in self.instances.values()
                if tag in i.tags and i.role == role and i.alive
            )

    def validate_tenants(self, config: TableConfig) -> None:
        """Reject table creation when a non-default tenant has no live
        members (the reference validates tenants before writing the
        table config; SURVEY §3.5)."""
        if config.server_tenant != "DefaultTenant" and not self._live_tenant_members(
            config.server_tenant, "server"
        ):
            raise ValueError(f"server tenant {config.server_tenant!r} has no live servers")
        if config.broker_tenant != "DefaultTenant" and not self._live_tenant_members(
            config.broker_tenant, "broker"
        ):
            raise ValueError(f"broker tenant {config.broker_tenant!r} has no live brokers")

    # -- rebalance -----------------------------------------------------
    def rebalance_table(
        self, physical_table: str, dry_run: bool = False
    ) -> Dict[str, Any]:
        """Recompute a balanced segment->server assignment over the
        current live tenant servers and (unless ``dry_run``) apply the
        diff: new replicas driven ONLINE, removed replicas DROPPED.

        The RebalanceTableCommand / Helix auto-rebalance analog: load
        per server is capped at ceil(total_replica_slots / num_servers)
        and existing placements are kept whenever the cap allows, so
        movement is minimal.  Segments with a CONSUMING replica are
        skipped (moving a live consumer is the LLC manager's job)."""
        with self._lock:
            config = self.table_configs.get(physical_table)
            if config is None:
                raise KeyError(f"no table {physical_table!r}")
            eligible = sorted(
                n
                for n, inst in self.instances.items()
                if inst.role == "server"
                and inst.alive
                and not inst.draining
                and config.server_tenant in inst.tags
            )
            if not eligible:
                raise RuntimeError("no live servers to rebalance onto")
            ideal = {
                s: dict(r) for s, r in self.ideal_states.get(physical_table, {}).items()
            }
        n_rep = min(config.replication, len(eligible))
        movable = sorted(s for s, r in ideal.items() if CONSUMING not in r.values())
        total_slots = n_rep * len(movable)
        cap = -(-total_slots // len(eligible)) if movable else 0  # ceil
        load = {s: 0 for s in eligible}
        target: Dict[str, List[str]] = {}
        # pass 1: keep existing eligible replicas while under the cap
        for seg in movable:
            kept = []
            for srv in sorted(ideal[seg]):
                if srv in load and load[srv] < cap and len(kept) < n_rep:
                    kept.append(srv)
                    load[srv] += 1
            target[seg] = kept
        # pass 2: fill open slots with the least-loaded servers
        for seg in movable:
            while len(target[seg]) < n_rep:
                srv = min(
                    (s for s in eligible if s not in target[seg]),
                    key=lambda s: (load[s], s),
                )
                target[seg].append(srv)
                load[srv] += 1
        added: Dict[str, List[str]] = {}
        removed: Dict[str, List[str]] = {}
        for seg in movable:
            state = next(iter(ideal[seg].values()), ONLINE)
            adds = [s for s in target[seg] if s not in ideal[seg]]
            drops = [s for s in ideal[seg] if s not in target[seg]]
            if adds:
                added[seg] = adds
            if drops:
                removed[seg] = drops
            if dry_run or (not adds and not drops):
                continue
            with self._lock:
                tbl = self.ideal_states.get(physical_table)
                if tbl is None or seg not in tbl:
                    # table/segment deleted since the snapshot was taken:
                    # don't resurrect it, drop it from the plan
                    added.pop(seg, None)
                    removed.pop(seg, None)
                    continue
                tbl[seg] = {s: state for s in target[seg]}
            for srv in adds:
                self._execute_transition(physical_table, seg, srv, state)
            for srv in drops:
                self._execute_transition(physical_table, seg, srv, DROPPED)
                with self._lock:
                    self.external_views.get(physical_table, {}).get(seg, {}).pop(srv, None)
        if not dry_run and (added or removed):
            self.persist_ideal_state(physical_table)
            self._notify_view(physical_table)
        return {
            "dryRun": dry_run,
            "segmentsMoved": len(set(added) | set(removed)),
            "added": added,
            "removed": removed,
            "target": {s: sorted(r) for s, r in target.items()},
        }

    # -- durability ---------------------------------------------------
    def persist_ideal_state(self, physical: str) -> None:
        if self.property_store is None:
            return
        # snapshot AND write under the lock: two concurrent mutators
        # must not be able to persist their snapshots out of order, or
        # the durable file could lose the newer update (writes are
        # small JSON records, so holding the lock is cheap)
        with self._lock:
            ideal = self.ideal_states.get(physical)
            if ideal is None:
                self.property_store.delete("idealstates", physical)
            else:
                self.property_store.put(
                    "idealstates", physical, {s: dict(r) for s, r in ideal.items()}
                )

    def persist_segment_record(self, physical: str, segment: str) -> None:
        """Write the JSON-serializable part of a segment's metadata
        record (the ZK segment-metadata analog: LLC offsets live in
        metadata.custom; ``dir`` is the controller-store download
        path).  Callables and in-memory segment objects are runtime
        wiring and are reattached on recovery."""
        if self.property_store is None:
            return
        import json as _json

        with self._lock:  # see persist_ideal_state on ordering
            info = self.segment_metadata.get((physical, segment))
            if info is None:
                self.property_store.delete(f"segments/{physical}", segment)
                return
            rec: Dict[str, Any] = {}
            meta = info.get("metadata")
            if meta is not None:
                rec["metadata"] = meta.to_json()
            for k, v in info.items():
                if k == "metadata" or callable(v):
                    continue
                try:
                    _json.dumps(v)
                except TypeError:
                    continue  # runtime wiring (segment objects, etc.)
                rec[k] = v
            self.property_store.put(f"segments/{physical}", segment, rec)

    # -- schema / table CRUD ------------------------------------------
    def add_schema(self, schema: Schema) -> None:
        with self._lock:
            self.schemas[schema.schema_name] = schema
        if self.property_store is not None:
            self.property_store.put("schemas", schema.schema_name, schema.to_json())

    def get_schema(self, name: str) -> Optional[Schema]:
        with self._lock:
            return self.schemas.get(name)

    def add_table(self, config: TableConfig) -> str:
        if not config.table_name.replace("_", "").replace("-", "").isalnum():
            # table names become store paths (segment store dirs,
            # property-store namespaces): refuse anything that could
            # traverse the filesystem
            raise ValueError(f"invalid table name {config.table_name!r}")
        with self._lock:
            physical = config.physical_name
            self.table_configs[physical] = config
            self.ideal_states.setdefault(physical, {})
            self.external_views.setdefault(physical, {})
        if self.property_store is not None:
            self.property_store.put("tables", physical, config.to_json())
        self.persist_ideal_state(physical)
        self._notify_view(physical)
        return physical

    def update_table_quota(
        self,
        physical: str,
        max_queries_per_second,
        burst_queries=None,
    ) -> None:
        """Live quota update/removal for a running table.  Persists the
        changed config, bumps the cluster-state version (so networked
        brokers pick it up on their next poll), and re-notifies the view
        (so in-process brokers re-apply the quota immediately).  Passing
        None removes the quota — brokers must CLEAR the bucket, not keep
        enforcing a stale one."""
        from pinot_tpu.common.tableconfig import QuotaConfig

        with self._lock:
            config = self.table_configs.get(physical)
            if config is None:
                raise KeyError(f"no such table {physical}")
            config.quota = QuotaConfig(
                storage=config.quota.storage,
                max_queries_per_second=max_queries_per_second,
                burst_queries=burst_queries,
            )
        if self.property_store is not None:
            self.property_store.put("tables", physical, config.to_json())
        # _notify_view bumps the version, re-sends routing AND re-applies
        # quota via BrokerStarter.on_view_change in-process; networked
        # brokers see the bumped version on their next clusterstate poll
        self._notify_view(physical)

    def update_table_slo(self, physical: str, slo) -> None:
        """Live SLO-objective update/removal for a running table
        (``SloConfig`` or None to fall back to env defaults).  Same
        propagation contract as ``update_table_quota``: persist the
        changed config, bump the cluster-state version (networked
        brokers re-apply on their next poll), re-notify the view
        (in-process brokers re-apply immediately)."""
        with self._lock:
            config = self.table_configs.get(physical)
            if config is None:
                raise KeyError(f"no such table {physical}")
            config.slo = slo
        if self.property_store is not None:
            self.property_store.put("tables", physical, config.to_json())
        self._notify_view(physical)

    def delete_table(self, physical: str) -> None:
        with self._lock:
            segs = list(self.ideal_states.get(physical, {}).keys())
        for seg in segs:
            self.delete_segment(physical, seg)
        with self._lock:
            self.table_configs.pop(physical, None)
            self.ideal_states.pop(physical, None)
            self.external_views.pop(physical, None)
        if self.property_store is not None:
            self.property_store.delete("tables", physical)
            self.property_store.delete("idealstates", physical)
            self.property_store.delete("streams", physical)
            self.property_store.delete_namespace(f"segments/{physical}")
        self._notify_view(physical)

    def tables(self) -> List[str]:
        with self._lock:
            return list(self.table_configs.keys())

    # -- segment assignment (ideal-state writes) ----------------------
    def _pick_servers(self, config: TableConfig) -> List[str]:
        with self._lock:
            servers = sorted(
                n
                for n, inst in self.instances.items()
                if inst.role == "server"
                and inst.alive
                and not inst.draining
                and config.server_tenant in inst.tags
            )
        if not servers:
            raise RuntimeError("no live servers to assign segment")
        n = min(config.replication, len(servers))
        # balanced round-robin over the sorted server list
        picked = [servers[(self._assign_rr + i) % len(servers)] for i in range(n)]
        self._assign_rr += 1
        return picked

    def add_segment(
        self,
        physical_table: str,
        metadata: SegmentMetadata,
        download_info: Dict[str, Any],
        target_state: str = ONLINE,
        servers: Optional[Sequence[str]] = None,
    ) -> List[str]:
        """Assign a segment to replicas and drive them to target_state
        (the upload path: PinotSegmentUploadRestletResource ->
        addNewOfflineSegment -> ideal state -> Helix ONLINE messages)."""
        with self._lock:
            config = self.table_configs[physical_table]
            chosen = list(servers) if servers else self._pick_servers(config)
            self.ideal_states[physical_table][metadata.segment_name] = {
                s: target_state for s in chosen
            }
            self.segment_metadata[(physical_table, metadata.segment_name)] = {
                "metadata": metadata,
                **download_info,
            }
        self.persist_ideal_state(physical_table)
        self.persist_segment_record(physical_table, metadata.segment_name)
        for server in chosen:
            self._execute_transition(
                physical_table, metadata.segment_name, server, target_state
            )
        self._notify_view(physical_table)
        return chosen

    def _execute_transition(
        self, table: str, segment: str, server: str, target: str
    ) -> None:
        with self._lock:
            participant = self._participants.get(server)
            info = dict(self.segment_metadata.get((table, segment), {}))
            if target == ONLINE:
                # configured inverted-index columns resolve from the
                # CURRENT table config at transition time (covers every
                # metadata writer incl. realtime commits, and config
                # edits apply on the next reload): servers pre-build
                # postings at load so the first needle query is warm
                cfg = self.table_configs.get(table)
                cols = cfg.indexing.inverted_index_columns if cfg else []
                if cols:
                    info["invertedIndexColumns"] = list(cols)
                # current schema rides along so the server can patch
                # schema-evolved segments with default columns at load
                # (SegmentPreProcessor -> BaseDefaultColumnHandler)
                schema = self.schemas.get(cfg.raw_name) if cfg else None
                if schema is not None:
                    info["schema"] = schema
            view = self.external_views.setdefault(table, {}).setdefault(segment, {})
        ok: Optional[bool] = False
        if participant is not None:
            try:
                ok = participant.on_transition(table, segment, target, info)
            except Exception:
                logger.exception("transition %s/%s -> %s on %s failed", table, segment, target, server)
                ok = False
        with self._lock:
            if ok is None:
                # pending: remote participant will report_state later
                view.setdefault(server, OFFLINE)
            else:
                view[server] = target if ok else ERROR

    def delete_segment(self, physical_table: str, segment: str) -> None:
        with self._lock:
            replicas = self.ideal_states.get(physical_table, {}).pop(segment, {})
            self.segment_metadata.pop((physical_table, segment), None)
        self.persist_ideal_state(physical_table)
        self.persist_segment_record(physical_table, segment)
        for server in replicas:
            self._execute_transition(physical_table, segment, server, DROPPED)
        with self._lock:
            self.external_views.get(physical_table, {}).pop(segment, None)
        self._notify_view(physical_table)

    def report_state(self, server: str, table: str, segment: str, state: str) -> None:
        """Async current-state report from a remote participant (the
        Helix CurrentState write a server makes after executing a
        queued transition message)."""
        with self._lock:
            tbl_view = self.external_views.setdefault(table, {})
            if segment not in self.ideal_states.get(table, {}):
                # segment deleted while the message was in flight; drop
                # any residual view entry instead of resurrecting it
                tbl_view.pop(segment, None)
                return
            if state == DROPPED:
                # the Helix analog deletes the current-state entry on
                # DROPPED — keeping it would leave a phantom replica
                # after a rebalance moved the segment off this server
                tbl_view.get(segment, {}).pop(server, None)
            else:
                tbl_view.setdefault(segment, {})[server] = state
        self._notify_view(table)

    # -- per-replica surgery (SelfStabilizer) --------------------------
    def add_segment_replica(self, table: str, segment: str, server: str) -> bool:
        """Add ``server`` to a segment's ideal replica set and drive it
        to the set's existing target state (the re-replication step: the
        new replica fetches from the controller's durable copy via the
        segment record's downloadUri/dir).  Idempotent."""
        with self._lock:
            replicas = self.ideal_states.get(table, {}).get(segment)
            if replicas is None or server in replicas:
                return False
            state = next(iter(replicas.values()), ONLINE)
            replicas[server] = state
        self.persist_ideal_state(table)
        self._execute_transition(table, segment, server, state)
        self._notify_view(table)
        return True

    def remove_segment_replica(self, table: str, segment: str, server: str) -> bool:
        """Remove one replica from a segment's ideal state.  A live
        holder gets a DROPPED transition (unload); a dead one gets no
        message — its queue was cleared on death, and re-registration
        reconciles against the ideal state that no longer names it."""
        with self._lock:
            replicas = self.ideal_states.get(table, {}).get(segment)
            if replicas is None or server not in replicas:
                return False
            del replicas[server]
            inst = self.instances.get(server)
            send_drop = inst is not None and inst.alive
        self.persist_ideal_state(table)
        if send_drop:
            self._execute_transition(table, segment, server, DROPPED)
        with self._lock:
            self.external_views.get(table, {}).get(segment, {}).pop(server, None)
        self._notify_view(table)
        return True

    def retire_segment(self, table: str, segment: str) -> List[str]:
        """Drop a segment from ideal state + metadata, transitioning
        only LIVE holders to DROPPED (unlike ``delete_segment``, which
        messages every replica).  Used by the stabilizer to retire a
        CONSUMING segment whose holders are all dead/draining so the
        realtime manager can re-create it on a live server at the last
        committed offset.  Returns the replica servers it held."""
        with self._lock:
            replicas = self.ideal_states.get(table, {}).pop(segment, {})
            self.segment_metadata.pop((table, segment), None)
            live = [
                s
                for s in replicas
                if (inst := self.instances.get(s)) is not None and inst.alive
            ]
        self.persist_ideal_state(table)
        self.persist_segment_record(table, segment)
        for server in live:
            self._execute_transition(table, segment, server, DROPPED)
        with self._lock:
            self.external_views.get(table, {}).pop(segment, None)
        self._notify_view(table)
        return sorted(replicas)

    def reset_segment(self, physical_table: str, segment: str, server: str) -> None:
        """ERROR -> OFFLINE -> retarget (the Helix error-reset analog)."""
        with self._lock:
            target = self.ideal_states.get(physical_table, {}).get(segment, {}).get(server)
        if target:
            self._execute_transition(physical_table, segment, server, target)
            self._notify_view(physical_table)

    # -- views --------------------------------------------------------
    def get_ideal_state(self, table: str) -> Dict[str, Dict[str, str]]:
        with self._lock:
            return {s: dict(r) for s, r in self.ideal_states.get(table, {}).items()}

    def get_external_view(self, table: str) -> Dict[str, Dict[str, str]]:
        with self._lock:
            return {s: dict(r) for s, r in self.external_views.get(table, {}).items()}

    def segments_of(self, table: str) -> List[str]:
        with self._lock:
            return list(self.ideal_states.get(table, {}).keys())

    def get_segment_metadata(self, table: str, segment: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self.segment_metadata.get((table, segment))
