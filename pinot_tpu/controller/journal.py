"""Append-only, CRC-framed op journal with periodic full snapshots for
the controller property store.

The reference's durable metadata plane is ZooKeeper: every znode write
lands in ZK's own transaction log + fuzzy snapshots, so a controller
can lose its local disk and recover the full cluster state from the
ensemble.  Our file-backed ``PropertyStore`` replaces ZK, so it needs
the same story locally: every mutation is framed into ``journal.log``
*before* the per-key JSON mirror file is rewritten, and a full-state
``snapshot.json`` is cut every N ops.  Recovery = snapshot +
journal-replay; a torn tail frame (crash mid-append) is truncated, not
fatal, and replay is idempotent because every op carries a
monotonically increasing ``seq`` that the snapshot also records.

Frame format (all integers big-endian)::

    u32 payload_length | u32 crc32(payload) | payload (UTF-8 JSON)

Payload::

    {"seq": N, "op": "put"|"delete"|"delete_ns", "ns": ..., "key": ...,
     "record": ...}

Epoch claims (PR 9 fencing) are ordinary journaled puts of the
``cluster/epoch`` record, so a restore from snapshot+journal preserves
the fencing invariant: the restored controller re-claims past the
highest journaled epoch and stale pre-disaster writers stay rejected.

fsync behaviour is governed by ``PINOT_TPU_DURABLE_FSYNC`` (default
on).  With it off, appends still hit the page cache in order — crash
recovery of the *process* is unaffected; only power loss can lose the
un-synced tail.
"""
from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Any, Callable, Dict, Optional, Tuple

from pinot_tpu.utils.fileio import atomic_write, fsync_dir

_FRAME = struct.Struct(">II")
# A frame longer than this is assumed to be garbage (torn/overwritten
# length word), not a real op: the whole property store is far smaller.
_MAX_FRAME_BYTES = 64 * 1024 * 1024

JOURNAL_DIR_NAME = ".journal"
LOG_NAME = "journal.log"
SNAPSHOT_NAME = "snapshot.json"


def durable_fsync_enabled() -> bool:
    """``PINOT_TPU_DURABLE_FSYNC`` knob; default on (durable)."""
    return os.environ.get("PINOT_TPU_DURABLE_FSYNC", "1") not in ("0", "false", "no")


def snapshot_every_default() -> int:
    try:
        return max(1, int(os.environ.get("PINOT_TPU_JOURNAL_SNAPSHOT_EVERY", "256")))
    except ValueError:
        return 256


# State shape shared with the property store: ns -> key -> record.
State = Dict[str, Dict[str, Any]]


def apply_op(state: State, op: Dict[str, Any]) -> None:
    """Apply one journaled op to an in-memory state mirror."""
    kind = op.get("op")
    ns = op.get("ns", "")
    if kind == "put":
        state.setdefault(ns, {})[op["key"]] = op.get("record")
    elif kind == "delete":
        state.get(ns, {}).pop(op.get("key"), None)
    elif kind == "delete_ns":
        prefix = ns + "/"
        for existing in [n for n in state if n == ns or n.startswith(prefix)]:
            del state[existing]


class MetadataJournal:
    """Single-writer op journal + snapshot pair under ``dir_path``.

    Not internally locked: the property store serializes all mutations
    (and recovery) under its own epoch-fence flock, which is the
    correct scope — cross-process, not just cross-thread.
    """

    def __init__(
        self,
        dir_path: str,
        fsync: Optional[bool] = None,
        snapshot_every: Optional[int] = None,
        on_event: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self.log_path = os.path.join(dir_path, LOG_NAME)
        self.snapshot_path = os.path.join(dir_path, SNAPSHOT_NAME)
        self.fsync = durable_fsync_enabled() if fsync is None else fsync
        self.snapshot_every = snapshot_every or snapshot_every_default()
        # on_event(name) lets the owner meter journal internals
        # (torn-tail truncations, corrupt snapshots) without the
        # journal depending on the metrics registry.
        self._on_event = on_event or (lambda name: None)
        self._fd: Optional[int] = None
        self.seq = 0  # last appended/recovered op seq
        self.ops_since_snapshot = 0
        self.torn_tail_truncations = 0

    # -- recovery ----------------------------------------------------

    def recover(self, fallback_state_fn: Optional[Callable[[], State]] = None) -> State:
        """Rebuild state from snapshot + journal replay.

        When no (valid) snapshot exists, ``fallback_state_fn()`` seeds
        the base state — the property store passes its on-disk record
        scan here so legacy/pre-journal stores are absorbed, with the
        journal's ops replayed on top in order (so journaled deletes
        still win over a stale mirror file).

        Torn tail frames are truncated off the log (counted via the
        ``journalTornTail`` event); a corrupt snapshot is quarantined
        aside and recovery proceeds from the journal alone.  Never
        raises for damaged journal/snapshot content.
        """
        state, snap_seq = self._load_snapshot()
        if snap_seq == 0 and not state and fallback_state_fn is not None:
            state = fallback_state_fn()
        self.seq = snap_seq
        applied = 0
        last_good = 0
        if os.path.exists(self.log_path):
            with open(self.log_path, "rb") as f:
                data = f.read()
            offset = 0
            while True:
                frame = self._read_frame(data, offset)
                if frame is None:
                    break
                op, offset = frame
                last_good = offset
                seq = int(op.get("seq", 0))
                if seq <= snap_seq:
                    continue  # already folded into the snapshot
                apply_op(state, op)
                self.seq = max(self.seq, seq)
                applied += 1
            if last_good < len(data):
                # torn tail: truncate to the last whole frame
                self.torn_tail_truncations += 1
                self._on_event("journalTornTail")
                with open(self.log_path, "r+b") as f:
                    f.truncate(last_good)
                if self.fsync:
                    with open(self.log_path, "rb") as f:
                        os.fsync(f.fileno())
        self.ops_since_snapshot = applied
        return state

    def _load_snapshot(self) -> Tuple[State, int]:
        if not os.path.exists(self.snapshot_path):
            return {}, 0
        try:
            with open(self.snapshot_path) as f:
                doc = json.load(f)
            state = doc["state"]
            if not isinstance(state, dict):
                raise ValueError("snapshot state is not a mapping")
            return state, int(doc.get("seq", 0))
        except (ValueError, KeyError, OSError, UnicodeDecodeError):
            self._on_event("corruptSnapshot")
            try:
                os.replace(
                    self.snapshot_path,
                    self.snapshot_path + ".corrupt.%d" % int(time.time() * 1000),
                )
            except OSError:
                pass
            return {}, 0

    @staticmethod
    def _read_frame(data: bytes, offset: int):
        """One frame at ``offset`` -> (op, next_offset), or None if the
        remaining bytes are not a whole valid frame (torn tail)."""
        if offset + _FRAME.size > len(data):
            return None
        length, crc = _FRAME.unpack_from(data, offset)
        if length == 0 or length > _MAX_FRAME_BYTES:
            return None
        start = offset + _FRAME.size
        end = start + length
        if end > len(data):
            return None
        payload = data[start:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return None
        try:
            op = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(op, dict):
            return None
        return op, end

    # -- append ------------------------------------------------------

    def append(self, op: Dict[str, Any]) -> int:
        """Frame + append one op; returns its assigned seq."""
        self.seq += 1
        op = dict(op)
        op["seq"] = self.seq
        payload = json.dumps(op, separators=(",", ":")).encode("utf-8")
        frame = _FRAME.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload
        if self._fd is None:
            self._fd = os.open(self.log_path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.write(self._fd, frame)
        if self.fsync:
            os.fsync(self._fd)
        self.ops_since_snapshot += 1
        return self.seq

    def should_snapshot(self) -> bool:
        return self.ops_since_snapshot >= self.snapshot_every

    def write_snapshot(self, state: State) -> None:
        """Atomically persist a full-state snapshot at the current seq
        and reset the log: crash between the snapshot replace and the
        log truncate is safe, since replay skips ops with
        ``seq <= snapshot.seq``."""
        atomic_write(
            self.snapshot_path,
            json.dumps({"seq": self.seq, "state": state}, separators=(",", ":")),
            fsync=self.fsync,
        )
        self.close()
        with open(self.log_path, "wb") as f:
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        if self.fsync:
            fsync_dir(self.dir)
        self.ops_since_snapshot = 0

    def log_size_bytes(self) -> int:
        try:
            return os.path.getsize(self.log_path)
        except OSError:
            return 0

    def close(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            finally:
                self._fd = None
