"""Controller ops UI (the pinot-dashboard Flask app analog).

The reference ships a separate Python Flask dashboard
(``pinot-dashboard/pinotui/__init__.py`` — routes for fabric/cluster
lists, per-table info, and a query console ``send_pql``) plus a
controller-side query proxy (``PqlQueryResource.java``). Here the same
surface is served by the controller's own HTTP front: stdlib-rendered
HTML pages over the live ResourceManager state, and a ``/pql`` proxy
that forwards to an alive broker.
"""
from __future__ import annotations

import html as _html
from typing import List
from urllib.parse import quote

_STYLE = """
<style>
  body { font-family: -apple-system, 'Segoe UI', sans-serif; margin: 2em;
         color: #1a1a1a; }
  h1, h2 { font-weight: 600; }
  a { color: #0b57d0; text-decoration: none; }
  a:hover { text-decoration: underline; }
  table { border-collapse: collapse; margin: 0.6em 0 1.4em; }
  th, td { border: 1px solid #ccc; padding: 4px 10px; text-align: left;
           font-size: 14px; }
  th { background: #f2f2f2; }
  tr.mismatch { background: #fdd; }
  .ok { color: #188038; } .bad { color: #c5221f; }
  .warn { color: #b06000; }
  nav { margin-bottom: 1.4em; }
  nav a { margin-right: 1.2em; }
  textarea { width: 100%; max-width: 56em; font-family: monospace; }
  pre { background: #f6f8fa; padding: 1em; max-width: 56em;
        overflow-x: auto; font-size: 13px; }
</style>
"""

_NAV = (
    "<nav><a href='/dashboard'>Cluster</a>"
    "<a href='/dashboard/query'>Query console</a>"
    "<a href='/dashboard/metrics'>Metrics</a>"
    "<a href='/dashboard/capacity'>Capacity</a>"
    "<a href='/dashboard/workload'>Workload</a>"
    "<a href='/dashboard/utilization'>Utilization</a>"
    "<a href='/dashboard/slo'>SLOs</a>"
    "<a href='/clusterstate'>Raw state (JSON)</a></nav>"
)


def _esc(v) -> str:
    return _html.escape(str(v))


def _page(title: str, body: List[str]) -> str:
    return (
        f"<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{_esc(title)}</title>{_STYLE}</head><body>"
        + _NAV
        + "\n".join(body)
        + "</body></html>"
    )


def render_home(ctrl) -> str:
    """Cluster overview: instances, tenants, tables (cluster_profile.html
    + fabric home of the reference dashboard)."""
    body = ["<h1>pinot_tpu cluster</h1>"]

    body.append("<h2>Instances</h2>")
    body.append(
        "<table><tr><th>name</th><th>role</th><th>status</th>"
        "<th>tags</th><th>url</th></tr>"
    )
    for inst in ctrl.resources.instances_snapshot():
        if not inst.alive:
            status = "<span class='bad'>down</span>"
        elif getattr(inst, "draining", False):
            status = "<span class='warn'>draining</span>"
        else:
            status = "<span class='ok'>alive</span>"
        tags = ", ".join(sorted(getattr(inst, "tags", []) or []))
        body.append(
            f"<tr><td>{_esc(inst.name)}</td><td>{_esc(inst.role)}</td>"
            f"<td>{status}</td><td>{_esc(tags)}</td>"
            f"<td>{_esc(inst.url or '')}</td></tr>"
        )
    body.append("</table>")

    tenants = ctrl.resources.list_tenants()
    if tenants:
        body.append("<h2>Tenants</h2>")
        body.append("<table><tr><th>tenant</th><th>servers</th><th>brokers</th></tr>")
        for t in sorted(tenants):
            body.append(
                f"<tr><td>{_esc(t)}</td>"
                f"<td>{_esc(', '.join(ctrl.resources.tenant_instances(t, 'server')))}</td>"
                f"<td>{_esc(', '.join(ctrl.resources.tenant_instances(t, 'broker')))}</td></tr>"
            )
        body.append("</table>")

    stabilizer = getattr(ctrl, "stabilizer", None)
    if stabilizer is not None:
        events = stabilizer.events()
        if events:
            body.append("<h2>Self-stabilization (recent heal events)</h2>")
            body.append(
                "<table><tr><th>event</th><th>server</th><th>table</th>"
                "<th>segment</th></tr>"
            )
            for ev in list(events)[-12:][::-1]:
                body.append(
                    f"<tr><td>{_esc(ev.get('event'))}</td>"
                    f"<td>{_esc(ev.get('server', ev.get('servers', '')))}</td>"
                    f"<td>{_esc(ev.get('table', ''))}</td>"
                    f"<td>{_esc(ev.get('segment', ''))}</td></tr>"
                )
            body.append("</table>")
            body.append(
                "<p>Full event ring + metrics: "
                "<a href='/debug/stabilizer'>/debug/stabilizer</a></p>"
            )

    body.append("<h2>Tables</h2>")
    body.append(
        "<table><tr><th>table</th><th>segments</th><th>docs</th>"
        "<th>size (bytes)</th><th>converged</th></tr>"
    )
    for table in ctrl.resources.tables():
        ideal = ctrl.resources.get_ideal_state(table)
        view = ctrl.resources.get_external_view(table)
        docs = 0
        for seg in ideal:
            info = ctrl.resources.get_segment_metadata(table, seg) or {}
            meta = info.get("metadata")
            docs += meta.num_docs if meta is not None else 0
        converged = all(ideal[s] == view.get(s, {}) for s in ideal)
        cv = (
            "<span class='ok'>yes</span>"
            if converged
            else "<span class='bad'>no</span>"
        )
        body.append(
            f"<tr><td><a href='/dashboard/table/{quote(table, safe='')}'>{_esc(table)}</a></td>"
            f"<td>{len(ideal)}</td><td>{docs}</td>"
            f"<td>{ctrl.store.table_size_bytes(table)}</td><td>{cv}</td></tr>"
        )
    body.append("</table>")
    return _page("pinot_tpu cluster", body)


def render_table(ctrl, table: str) -> str:
    """Per-table page: schema + per-segment ideal vs external state
    (table_info.html analog; highlights unconverged segments like the
    controller TableViews resource)."""
    body = [f"<h1>{_esc(table)}</h1>"]

    raw = table.rsplit("_", 1)[0]
    schema = ctrl.resources.get_schema(raw) or ctrl.resources.get_schema(table)
    if schema is not None:
        body.append("<h2>Schema</h2>")
        body.append("<table><tr><th>column</th><th>type</th><th>field</th></tr>")
        for spec in schema.all_fields():
            body.append(
                f"<tr><td>{_esc(spec.name)}</td><td>{_esc(spec.data_type.name)}</td>"
                f"<td>{_esc(spec.field_type.name)}</td></tr>"
            )
        body.append("</table>")

    ideal = ctrl.resources.get_ideal_state(table)
    view = ctrl.resources.get_external_view(table)
    body.append("<h2>Segments</h2>")
    body.append(
        "<table><tr><th>segment</th><th>ideal</th><th>external</th>"
        "<th>docs</th></tr>"
    )
    for seg in sorted(ideal):
        info = ctrl.resources.get_segment_metadata(table, seg) or {}
        meta = info.get("metadata")
        docs = meta.num_docs if meta is not None else ""
        cls = " class='mismatch'" if ideal[seg] != view.get(seg, {}) else ""
        body.append(
            f"<tr{cls}><td>{_esc(seg)}</td><td>{_esc(ideal[seg])}</td>"
            f"<td>{_esc(view.get(seg, {}))}</td><td>{docs}</td></tr>"
        )
    body.append("</table>")
    return _page(table, body)


def _metrics_rows(body: List[str], snap: dict) -> None:
    """One registry snapshot -> meter/timer/gauge rows."""
    meters = snap.get("meters") or {}
    timers = snap.get("timers") or {}
    gauges = snap.get("gauges") or {}
    if not (meters or timers or gauges):
        return
    body.append(
        "<table><tr><th>metric</th><th>kind</th><th>count</th>"
        "<th>rate 1m</th><th>mean ms</th><th>p95 ms</th><th>value</th></tr>"
    )
    for name in sorted(meters):
        m = meters[name]
        body.append(
            f"<tr><td>{_esc(name)}</td><td>meter</td><td>{m.get('count')}</td>"
            f"<td>{m.get('rate1m', m.get('rate'))}</td><td></td><td></td><td></td></tr>"
        )
    for name in sorted(timers):
        t = timers[name]
        body.append(
            f"<tr><td>{_esc(name)}</td><td>timer</td><td>{t.get('count')}</td>"
            f"<td></td><td>{t.get('meanMs')}</td><td>{t.get('p95Ms')}</td><td></td></tr>"
        )
    for name in sorted(gauges):
        body.append(
            f"<tr><td>{_esc(name)}</td><td>gauge</td><td></td><td></td>"
            f"<td></td><td></td><td>{_esc(gauges[name])}</td></tr>"
        )
    body.append("</table>")


def render_metrics(ctrl, cluster_metrics: dict) -> str:
    """Cluster-wide metrics page: the controller's own registries plus
    the ``/debug/metrics`` snapshot of every alive instance that
    advertises an HTTP surface (``collect_cluster_metrics``)."""
    body = ["<h1>Cluster metrics</h1>"]
    body.append(
        "<p>Prometheus exposition: controller <a href='/metrics'>/metrics</a>; "
        "every broker and server serves its own <code>/metrics</code> and "
        "<code>/debug/metrics</code>. Raw aggregate: "
        "<a href='/debug/clustermetrics'>/debug/clustermetrics</a>.</p>"
    )
    for scope, snap in (cluster_metrics.get("controller") or {}).items():
        body.append(f"<h2>controller · {_esc(scope)}</h2>")
        _metrics_rows(body, snap or {})
    for name, entry in sorted((cluster_metrics.get("instances") or {}).items()):
        body.append(
            f"<h2>{_esc(entry.get('role', '?'))} · {_esc(name)}</h2>"
        )
        if entry.get("error"):
            body.append(f"<p class='bad'>unreachable: {_esc(entry['error'])}</p>")
            continue
        payload = entry.get("metrics") or {}
        # broker /debug/metrics is a bare registry snapshot; the server
        # one nests it under "metrics" next to scheduler/lane state
        snap = payload.get("metrics") if isinstance(payload.get("metrics"), dict) else payload
        _metrics_rows(body, snap or {})
        heal = payload.get("selfHealing")
        if heal:
            body.append("<table><tr><th>selfHealing</th><th>count</th></tr>")
            for k in sorted(heal):
                body.append(f"<tr><td>{_esc(k)}</td><td>{_esc(heal[k])}</td></tr>")
            body.append("</table>")
    return _page("Cluster metrics", body)


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return str(n)


def render_capacity(ctrl, capacity: dict) -> str:
    """Cluster capacity & cost page (``collect_capacity`` rollup): HBM
    staging ledgers and ingest lag per server, per-table cost rates —
    the one page that shows who is burning the cluster."""
    totals = capacity.get("totals") or {}
    body = ["<h1>Capacity &amp; cost</h1>"]
    body.append(
        f"<p>Staged HBM (all servers): <b>{_fmt_bytes(totals.get('stagedBytes', 0))}</b>"
        f" &middot; ingest lag: <b>{totals.get('ingestLagRows', 0)}</b> rows"
        f" &middot; raw JSON: <a href='/debug/capacity'>/debug/capacity</a></p>"
    )
    unreachable = capacity.get("unreachable") or {}
    if unreachable:
        names = ", ".join(
            f"{_esc(n)} ({_esc(e.get('role', '?'))})"
            for n, e in sorted(unreachable.items())
        )
        body.append(
            f"<p class='bad'>Partial rollup — unreachable: {names}</p>"
        )

    body.append("<h2>Servers — HBM staging ledger &amp; ingest</h2>")
    body.append(
        "<table><tr><th>server</th><th>staged</th><th>high-water</th>"
        "<th>tables</th><th>evicted</th><th>qinput cache</th>"
        "<th>ingest lag (rows)</th><th>ingest rows/s (1m)</th></tr>"
    )
    for name, entry in sorted((capacity.get("servers") or {}).items()):
        if entry.get("error"):
            body.append(
                f"<tr><td>{_esc(name)}</td><td colspan='7' class='bad'>"
                f"unreachable: {_esc(entry['error'])}</td></tr>"
            )
            continue
        hbm = entry.get("hbm") or {}
        lag = entry.get("ingestLag") or {}
        lag_str = (
            ", ".join(f"{_esc(k)}={v}" for k, v in sorted(lag.items())) or "0"
        )
        rows = entry.get("ingestRows") or {}
        body.append(
            f"<tr><td>{_esc(name)}</td>"
            f"<td>{_fmt_bytes(hbm.get('stagedBytes', 0))}</td>"
            f"<td>{_fmt_bytes(hbm.get('highWatermarkBytes', 0))}</td>"
            f"<td>{hbm.get('stagedTables', 0)}</td>"
            f"<td>{_fmt_bytes(hbm.get('evictedBytes', 0))}</td>"
            f"<td>{_fmt_bytes(hbm.get('qinputCacheBytes', 0))}</td>"
            f"<td>{_esc(lag_str)}</td>"
            f"<td>{rows.get('rate1m', 0)}</td></tr>"
        )
    body.append("</table>")

    body.append("<h2>Per-table cost (broker-attributed)</h2>")
    tables = capacity.get("tables") or {}
    if not tables:
        body.append("<p>No per-table cost recorded yet (no queries).</p>")
    else:
        body.append(
            "<table><tr><th>table</th><th>docs scanned</th>"
            "<th>docs/s (1m)</th><th>bytes scanned</th><th>bytes/s (1m)</th></tr>"
        )
        ordered = sorted(
            tables.items(),
            key=lambda kv: -float(kv[1].get("bytesScanned", 0) or 0),
        )
        for tname, t in ordered:
            body.append(
                f"<tr><td>{_esc(tname)}</td>"
                f"<td>{t.get('docsScanned', 0)}</td>"
                f"<td>{t.get('docsScannedRate1m', 0)}</td>"
                f"<td>{_fmt_bytes(t.get('bytesScanned', 0))}</td>"
                f"<td>{_fmt_bytes(t.get('bytesScannedRate1m', 0))}/s</td></tr>"
            )
        body.append("</table>")
    return _page("Capacity & cost", body)


def _workload_table(body: List[str], plans: List[dict], title: str) -> None:
    body.append(f"<h2>{_esc(title)}</h2>")
    if not plans:
        body.append("<p>No plans recorded yet (no queries).</p>")
        return
    body.append(
        "<table><tr><th>digest</th><th>shape</th><th>table</th>"
        "<th>execs</th><th>shed</th><th>failed</th><th>docs</th>"
        "<th>bytes</th><th>device ms</th><th>host ms</th><th>tier mix</th></tr>"
    )
    for p in plans:
        cost = p.get("cost") or {}
        tiers = ", ".join(
            f"{k[len('segments'):]}={int(v)}"
            for k, v in sorted(cost.items())
            if k.startswith("segments")
        )
        body.append(
            f"<tr><td><code>{_esc(p.get('digest'))}</code></td>"
            f"<td>{_esc(p.get('summary', ''))}</td>"
            f"<td>{_esc(p.get('table', ''))}</td>"
            f"<td>{p.get('count', 0)}</td>"
            f"<td>{p.get('shedCount', 0)}</td>"
            f"<td>{p.get('failedCount', 0)}</td>"
            f"<td>{p.get('docsScanned', 0)}</td>"
            f"<td>{_fmt_bytes(cost.get('bytesScanned', 0))}</td>"
            f"<td>{round(float(cost.get('deviceMs', 0)), 1)}</td>"
            f"<td>{round(float(cost.get('hostMs', 0)), 1)}</td>"
            f"<td>{_esc(tiers)}</td></tr>"
        )
    body.append("</table>")


def render_workload(ctrl, workload: dict) -> str:
    """Cluster workload page (``collect_workload`` roll-up): the plan
    shapes dominating the fleet by frequency and by cost — the direct
    input to "which plan shapes should batched serving target?"."""
    body = ["<h1>Workload — plan shapes</h1>"]
    body.append(
        f"<p>Brokers polled: <b>{workload.get('brokers', 0)}</b>"
        f" &middot; distinct shapes: <b>{workload.get('digests', 0)}</b>"
        f" &middot; responses recorded: <b>{workload.get('totalRecorded', 0)}</b>"
        f" &middot; raw JSON: <a href='/debug/workload'>/debug/workload</a></p>"
    )
    unreachable = workload.get("unreachable") or {}
    if unreachable:
        names = ", ".join(_esc(n) for n in sorted(unreachable))
        body.append(f"<p class='bad'>Partial roll-up — unreachable: {names}</p>")
    _workload_table(body, workload.get("topByCount") or [], "Top by frequency")
    _workload_table(body, workload.get("topByCost") or [], "Top by cost")
    return _page("Workload", body)


def _fmt_frac(v) -> str:
    if v is None:
        return "n/a (no peak declared)"
    try:
        return f"{float(v) * 100.0:.2f}%"
    except (TypeError, ValueError):
        return str(v)


def render_utilization(ctrl, util: dict) -> str:
    """Fleet device-utilization page (``collect_utilization`` rollup):
    per-server lane occupancy, transfer totals, achieved-vs-peak
    roofline rates, profiler state, and the top-K underutilized plan
    shapes — the page the throughput arc (multichip, batched serving,
    bit-sliced kernels) is gated on."""
    totals = util.get("totals") or {}
    occ = util.get("occupancy") or {}
    body = ["<h1>Device utilization</h1>"]
    body.append(
        f"<p>Fleet achieved: <b>{_fmt_bytes(totals.get('achievedBytesPerSec', 0))}/s</b>"
        f" over {totals.get('queries', 0)} recent device queries"
        f" &middot; roofline: <b>{_fmt_frac(util.get('rooflineFraction'))}</b>"
        f" &middot; mean busy: <b>{_fmt_frac(occ.get('meanBusyFraction', 0))}</b>"
        f" &middot; active profiles: <b>{util.get('profilesActive', 0)}</b>"
        f" &middot; raw JSON: <a href='/debug/utilization'>/debug/utilization</a></p>"
    )
    unreachable = util.get("unreachable") or {}
    if unreachable:
        names = ", ".join(_esc(n) for n in sorted(unreachable))
        body.append(f"<p class='bad'>Partial rollup — unreachable: {names}</p>")

    body.append("<h2>Servers</h2>")
    servers = util.get("servers") or {}
    if not servers:
        body.append("<p>No servers with an admin HTTP surface registered.</p>")
    else:
        body.append(
            "<table><tr><th>server</th><th>platform</th><th>busy</th>"
            "<th>avg queue</th><th>H2D</th><th>D2H</th>"
            "<th>achieved B/s</th><th>roofline</th><th>profiler</th></tr>"
        )
        for name, entry in sorted(servers.items()):
            dev = entry.get("device") or {}
            plat = dev.get("platform") or {}
            o = dev.get("occupancy") or {}
            tr = dev.get("transfers") or {}
            recent = dev.get("recent") or {}
            prof = dev.get("profiler") or {}
            prof_str = (
                "<span class='warn'>capturing</span>"
                if prof.get("active")
                else "idle"
            )
            body.append(
                f"<tr><td>{_esc(name)}</td>"
                f"<td>{_esc(plat.get('deviceKind') or plat.get('platform') or '?')}</td>"
                f"<td>{_fmt_frac(o.get('busyFraction', 0))}</td>"
                f"<td>{o.get('avgQueueDepth', 0)}</td>"
                f"<td>{_fmt_bytes(tr.get('h2dBytes', 0))}</td>"
                f"<td>{_fmt_bytes(tr.get('d2hBytes', 0))}</td>"
                f"<td>{_fmt_bytes(recent.get('achievedBytesPerSec', 0))}/s</td>"
                f"<td>{_fmt_frac(recent.get('rooflineFraction'))}</td>"
                f"<td>{prof_str}</td></tr>"
            )
        body.append("</table>")

    body.append("<h2>Most underutilized plan shapes (device-executed)</h2>")
    plans = util.get("underutilizedPlans") or []
    if not plans:
        body.append("<p>No device-executed plan shapes recorded yet.</p>")
    else:
        body.append(
            "<table><tr><th>server</th><th>digest</th><th>shape</th>"
            "<th>table</th><th>execs</th><th>device ms</th>"
            "<th>achieved B/s</th><th>roofline</th></tr>"
        )
        for p in plans:
            body.append(
                f"<tr><td>{_esc(p.get('server'))}</td>"
                f"<td><code>{_esc(p.get('digest'))}</code></td>"
                f"<td>{_esc(p.get('summary', ''))}</td>"
                f"<td>{_esc(p.get('table', ''))}</td>"
                f"<td>{p.get('count', 0)}</td>"
                f"<td>{round(float(p.get('deviceMs', 0)), 1)}</td>"
                f"<td>{_fmt_bytes(p.get('achievedBytesPerSec', 0))}/s</td>"
                f"<td>{_fmt_frac(p.get('rooflineFraction'))}</td></tr>"
            )
        body.append("</table>")
    return _page("Device utilization", body)


def _fmt_burn(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    cls = "bad" if f >= 1.0 else ("warn" if f >= 0.5 else "ok")
    return f"<span class='{cls}'>{f:.2f}</span>"


def render_slo(ctrl, slo: dict) -> str:
    """Fleet SLO page (``collect_slo`` rollup): per-table error-budget
    burn rates over the fast/slow windows, worst-burning tables first —
    the page that names the table an operator should look at when the
    ``slo.burning`` gauge fires."""
    tables = slo.get("tables") or {}
    burning = slo.get("burningTables") or []
    cfg = slo.get("config") or {}
    body = ["<h1>SLO burn rates</h1>"]
    head = (
        f"<span class='bad'>{len(burning)} table(s) burning: "
        f"{_esc(', '.join(burning))}</span>"
        if burning
        else "<span class='ok'>no table burning</span>"
    )
    body.append(
        f"<p>{head} &middot; brokers polled: <b>{slo.get('brokers', 0)}</b>"
        f" &middot; windows: {cfg.get('fastWindowS', '?')}s /"
        f" {cfg.get('slowWindowS', '?')}s, threshold"
        f" {cfg.get('burnThreshold', '?')}"
        f" &middot; raw JSON: <a href='/debug/slo'>/debug/slo</a></p>"
    )
    unreachable = slo.get("unreachable") or {}
    if unreachable:
        names = ", ".join(_esc(n) for n in sorted(unreachable))
        body.append(f"<p class='bad'>Partial rollup — unreachable: {names}</p>")
    if not tables:
        body.append("<p>No per-table SLO traffic observed yet.</p>")
        return _page("SLOs", body)
    body.append(
        "<table><tr><th>table</th><th>burn (fast)</th><th>burn (slow)</th>"
        "<th>burning</th><th>objective</th><th>brokers</th></tr>"
    )
    for name in slo.get("worstBurning") or sorted(tables):
        t = tables.get(name) or {}
        obj = t.get("objective") or {}
        burn = (
            "<span class='bad'>YES</span>"
            if t.get("burning")
            else "<span class='ok'>no</span>"
        )
        obj_str = (
            f"p{100 * float(obj.get('latencyTarget', 0) or 0):g} &lt; "
            f"{obj.get('latencyMs', '?')}ms, avail "
            f"{100 * float(obj.get('availabilityTarget', 0) or 0):g}%"
            if obj
            else "?"
        )
        body.append(
            f"<tr><td>{_esc(name)}</td>"
            f"<td>{_fmt_burn(t.get('burnRate5m', 0))}</td>"
            f"<td>{_fmt_burn(t.get('burnRate1h', 0))}</td>"
            f"<td>{burn}</td><td>{obj_str}</td>"
            f"<td>{_esc(', '.join(sorted(t.get('byBroker') or {})))}</td></tr>"
        )
    body.append("</table>")
    body.append(
        "<p>burn = bad-fraction / error-budget per window; a table is "
        "burning only when BOTH windows exceed the threshold. History: "
        "<a href='/debug/history?series=slo.'>/debug/history?series=slo.</a>"
        " &middot; tails: on each broker at <code>/debug/tails</code></p>"
    )
    return _page("SLOs", body)


def render_query_console() -> str:
    """Query console page (query_console.html analog): posts PQL to the
    controller's /pql proxy, renders the broker JSON response."""
    body = [
        "<h1>Query console</h1>",
        "<form id='f'>",
        "<textarea id='pql' rows='4' placeholder='SELECT count(*) FROM myTable'>"
        "</textarea><br>",
        "<label><input type='checkbox' id='trace'> trace</label> ",
        "<button type='submit'>Run</button>",
        "</form>",
        "<pre id='out'></pre>",
        """<script>
document.getElementById('f').addEventListener('submit', async (e) => {
  e.preventDefault();
  const out = document.getElementById('out');
  out.textContent = 'running...';
  try {
    const r = await fetch('/pql', {
      method: 'POST',
      headers: {'Content-Type': 'application/json'},
      body: JSON.stringify({
        pql: document.getElementById('pql').value,
        trace: document.getElementById('trace').checked,
      }),
    });
    out.textContent = JSON.stringify(await r.json(), null, 2);
  } catch (err) { out.textContent = String(err); }
});
</script>""",
    ]
    return _page("Query console", body)
