"""Durable controller property store.

The reference keeps all cluster metadata — schemas, table configs,
ideal states, per-segment ZK metadata (incl. LLC offset checkpoints) —
in the ZooKeeper property store
(``PinotHelixResourceManager.java:103``, ``pinot-common/.../metadata/``),
so a controller restart recovers the whole cluster from ZK.  This is
the single-controller analog: one JSON file per record under the
controller's data dir, written atomically (tmp + rename) so a crash
mid-write can never corrupt a record.

Durability (the ZK-transaction-log analog): every mutation is first
appended to a CRC-framed op journal (``.journal/journal.log``) and a
full-state snapshot is cut periodically (``.journal/snapshot.json``) —
see ``controller/journal.py``.  The per-key JSON files become a read
mirror: a missing or corrupted record file is healed from the
journal-recovered in-memory state instead of crashing the reader, and
a garbled record that has no journal backing surfaces as a typed
``CorruptRecordError`` with the damaged file quarantined aside
(``<name>.json.corrupt.<ms>`` — the PR 3 segment-quarantine idiom).
fsync of the journal is governed by ``PINOT_TPU_DURABLE_FSYNC``
(default on); the mirror files skip fsync since the journal, not the
mirror, is the recovery source of truth.

Namespaces:
  schemas/<name>.json          Schema.to_json()
  tables/<physical>.json       TableConfig.to_json()
  idealstates/<physical>.json  {segment -> {server -> target state}}
  segments/<physical>/<segment>.json  segment record: metadata +
                               download dir + realtime partition/offset
  streams/<physical>.json      stream-provider descriptor for realtime
                               tables (so consumption resumes)
  cluster/epoch.json           the controller-incarnation fencing token

Epoch fencing (the ZK leader-generation analog): a controller claims
authority at construction by bumping ``cluster/epoch`` and becomes the
store's writer; every subsequent ``put``/``delete`` re-reads the stored
epoch and raises a typed ``StaleEpochError`` when a NEWER incarnation
has claimed the store since — so a partitioned-away or zombie
controller cannot clobber the live one's state (split-brain safety).
A store without a writer epoch (bare/test use) is unfenced.  Epoch
claims go through the journal like every other put, so a restore from
snapshot+journal preserves the fencing invariant.
"""
from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from pinot_tpu.common.fencing import StaleEpochError
from pinot_tpu.controller.journal import JOURNAL_DIR_NAME, MetadataJournal
from pinot_tpu.utils.fileio import atomic_write
from pinot_tpu.utils.metrics import ControllerMetrics

CLUSTER_NS = "cluster"
EPOCH_KEY = "epoch"
_FENCE_LOCK_FILE = ".fence.lock"  # never matches an encoded record name

_SAFE = "-_"  # NOT '.', or a '..' component would survive encoding


class CorruptRecordError(Exception):
    """A property-store record file is unreadable/garbled and has no
    journal backing to heal from.  The damaged file has been
    quarantined aside (``<path>.corrupt.<ms>``)."""

    def __init__(self, namespace: str, key: str, path: str, cause: Exception) -> None:
        super().__init__(
            f"corrupt property-store record {namespace}/{key} at {path}: {cause!r}"
        )
        self.namespace = namespace
        self.key = key
        self.path = path
        self.cause = cause


def _encode_key(key: str) -> str:
    """Filesystem-safe record name (segment names contain '__', table
    names are alnum+underscore; escape anything else)."""
    out = []
    for ch in key:
        if ch.isalnum() or ch in _SAFE or ch == "_":
            out.append(ch)
        else:
            out.append(f"%{ord(ch):02x}")
    return "".join(out) + ".json"


def _decode_name(raw: str) -> str:
    """Reverse of ``_encode_key`` (without the .json suffix)."""
    parts = []
    i = 0
    while i < len(raw):
        if raw[i] == "%" and i + 2 < len(raw) + 1:
            try:
                parts.append(chr(int(raw[i + 1 : i + 3], 16)))
                i += 3
                continue
            except ValueError:
                pass
        parts.append(raw[i])
        i += 1
    return "".join(parts)


class PropertyStore:
    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir
        self._lock = threading.Lock()
        # None = unfenced (bare/test stores); set via claim_epoch()
        self._writer_epoch: Optional[int] = None
        # persistent fence-lock fd (opened on first fenced use): flock
        # is per open-file-description, so one long-lived fd gives
        # cross-PROCESS exclusion without 3 syscalls per write; the
        # thread lock above covers threads sharing this fd
        self._fence_fd = None
        os.makedirs(base_dir, exist_ok=True)
        self.metrics = ControllerMetrics("durability")
        for m in (
            "durability.journalAppends",
            "durability.snapshots",
            "durability.corruptRecords",
            "durability.recordsHealed",
            "durability.journalTornTailTruncations",
            "durability.corruptSnapshots",
        ):
            self.metrics.meter(m)
        self._journal = MetadataJournal(
            os.path.join(base_dir, JOURNAL_DIR_NAME), on_event=self._journal_event
        )
        # journal-recovered state mirror: ns -> key -> record.  Guarded
        # by its own lock (NOT self._lock): get() must stay callable
        # from inside _exclusive (claim_epoch -> stored_epoch -> get).
        self._mem_lock = threading.Lock()
        # recovery runs under the cross-process fence lock so a live
        # writer's in-flight append cannot interleave with our replay
        with self._exclusive(force_flock=True):
            self._mem: Dict[str, Dict[str, Any]] = self._journal.recover(
                fallback_state_fn=self._scan_disk_state
            )

    def _journal_event(self, name: str) -> None:
        if name == "journalTornTail":
            self.metrics.meter("durability.journalTornTailTruncations").mark()
        elif name == "corruptSnapshot":
            self.metrics.meter("durability.corruptSnapshots").mark()

    def _ns_dir(self, namespace: str) -> str:
        # encode each namespace component too: namespaces embed table
        # names, and a hostile name must not escape the store dir
        parts = [_encode_key(p)[: -len(".json")] for p in namespace.split("/") if p]
        return os.path.join(self.base_dir, *parts)

    def _path(self, namespace: str, key: str) -> str:
        return os.path.join(self._ns_dir(namespace), _encode_key(key))

    # -- epoch fencing -------------------------------------------------
    @contextmanager
    def _exclusive(self, force_flock: bool = False):
        """Thread lock + cross-PROCESS file lock over the store: the
        fence check and the write it guards must be one atomic unit, or
        a zombie's in-flight write could land just after a newer
        incarnation claims the store (check-then-act race).  Unfenced
        stores (no claimed epoch — bare/test use) skip the file lock:
        their fence check is a no-op, so the thread lock alone is the
        pre-fencing behavior."""
        with self._lock:
            if self._writer_epoch is None and not force_flock:
                yield
                return
            if self._fence_fd is None:
                self._fence_fd = open(
                    os.path.join(self.base_dir, _FENCE_LOCK_FILE), "a+b"
                )
            fcntl.flock(self._fence_fd, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(self._fence_fd, fcntl.LOCK_UN)

    def stored_epoch(self) -> int:
        """The incarnation currently holding the store (0 = unclaimed).
        Read from disk every time: the whole point is seeing a NEWER
        claimant that may live in another process.  Routed through
        ``get`` so a damaged epoch record heals from the journal."""
        try:
            rec = self.get(CLUSTER_NS, EPOCH_KEY)
        except CorruptRecordError:
            return 0
        if not rec:
            return 0
        try:
            return int(rec.get("epoch", 0))
        except (TypeError, ValueError):
            return 0

    @property
    def writer_epoch(self) -> Optional[int]:
        return self._writer_epoch

    def claim_epoch(self) -> int:
        """Claim write authority: bump ``cluster/epoch`` and become the
        store's writer.  Every OLDER incarnation's writes are rejected
        from this moment (their next ``put``/``delete`` raises
        ``StaleEpochError``)."""
        with self._exclusive(force_flock=True):
            epoch = self.stored_epoch() + 1
            record = {"epoch": epoch}
            path = self._path(CLUSTER_NS, EPOCH_KEY)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            self._append_and_mirror(CLUSTER_NS, EPOCH_KEY, record, path)
            self._writer_epoch = epoch
        return epoch

    def _check_fence(self) -> None:
        if self._writer_epoch is None:
            return
        stored = self.stored_epoch()
        if stored > self._writer_epoch:
            raise StaleEpochError(
                f"property store claimed by epoch {stored}; this writer "
                f"holds stale epoch {self._writer_epoch}",
                stale=self._writer_epoch,
                current=stored,
            )

    # -- journaled mutation helpers ------------------------------------

    def _append_and_mirror(
        self, namespace: str, key: str, record: Dict[str, Any], path: str
    ) -> None:
        """WAL order, caller holds _exclusive: journal first, then the
        per-key mirror file (un-fsynced — the journal is the recovery
        source), then the in-memory state, then maybe snapshot."""
        self._journal.append(
            {"op": "put", "ns": namespace, "key": key, "record": record}
        )
        self.metrics.meter("durability.journalAppends").mark()
        atomic_write(path, json.dumps(record), fsync=False)
        with self._mem_lock:
            self._mem.setdefault(namespace, {})[key] = record
        self._maybe_snapshot()

    def _maybe_snapshot(self) -> None:
        if self._journal.should_snapshot():
            self._journal.write_snapshot(self._full_state())
            self.metrics.meter("durability.snapshots").mark()

    def snapshot_now(self) -> None:
        """Force a full-state snapshot + journal reset (backup prep)."""
        with self._exclusive():
            self._journal.write_snapshot(self._full_state())
            self.metrics.meter("durability.snapshots").mark()

    def _full_state(self) -> Dict[str, Dict[str, Any]]:
        """Disk mirror overlaid with journal state (journal wins): the
        scan picks up pre-journal legacy records, the overlay carries
        anything whose mirror write hasn't landed."""
        state = self._scan_disk_state()
        with self._mem_lock:
            for ns, records in self._mem.items():
                state.setdefault(ns, {}).update(records)
        return state

    def _scan_disk_state(self) -> Dict[str, Dict[str, Any]]:
        """Read every record file under the store into state shape.
        Unreadable records are quarantined aside and skipped (they can
        still heal later if the journal knows them)."""
        state: Dict[str, Dict[str, Any]] = {}
        for dirpath, dirnames, filenames in os.walk(self.base_dir):
            dirnames[:] = [d for d in dirnames if d != JOURNAL_DIR_NAME]
            rel = os.path.relpath(dirpath, self.base_dir)
            if rel == ".":
                continue  # records always live inside a namespace dir
            namespace = "/".join(_decode_name(p) for p in rel.split(os.sep))
            for fn in filenames:
                if not fn.endswith(".json") or ".corrupt." in fn:
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    with open(path) as f:
                        record = json.load(f)
                except (ValueError, UnicodeDecodeError, OSError):
                    self._quarantine_file(path)
                    continue
                state.setdefault(namespace, {})[_decode_name(fn[: -len(".json")])] = record
        return state

    def _quarantine_file(self, path: str) -> None:
        self.metrics.meter("durability.corruptRecords").mark()
        try:
            os.replace(path, path + ".corrupt.%d" % int(time.time() * 1000))
        except OSError:
            pass

    # -- record API ----------------------------------------------------

    def put(self, namespace: str, key: str, record: Dict[str, Any]) -> None:
        path = self._path(namespace, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._exclusive():
            self._check_fence()
            self._append_and_mirror(namespace, key, record, path)

    def get(self, namespace: str, key: str) -> Optional[Dict[str, Any]]:
        path = self._path(namespace, key)
        if not os.path.exists(path):
            return self._heal_from_mem(namespace, key, path)
        try:
            with open(path) as f:
                return json.load(f)
        except (ValueError, UnicodeDecodeError, OSError) as e:
            # truncated/garbled record: quarantine the damaged file and
            # heal from the journal state if it knows this record
            self._quarantine_file(path)
            healed = self._heal_from_mem(namespace, key, path)
            if healed is not None:
                return healed
            raise CorruptRecordError(namespace, key, path, e) from e

    def _heal_from_mem(
        self, namespace: str, key: str, path: str
    ) -> Optional[Dict[str, Any]]:
        with self._mem_lock:
            rec = self._mem.get(namespace, {}).get(key)
            if rec is None:
                return None
            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write(path, json.dumps(rec), fsync=False)
            self.metrics.meter("durability.recordsHealed").mark()
            # round-trip so callers can't mutate the journal state
            return json.loads(json.dumps(rec))

    def delete(self, namespace: str, key: str) -> None:
        path = self._path(namespace, key)
        with self._exclusive():
            self._check_fence()
            self._journal.append({"op": "delete", "ns": namespace, "key": key})
            self.metrics.meter("durability.journalAppends").mark()
            if os.path.exists(path):
                os.unlink(path)
            with self._mem_lock:
                self._mem.get(namespace, {}).pop(key, None)
            self._maybe_snapshot()

    def list_keys(self, namespace: str) -> List[str]:
        d = self._ns_dir(namespace)
        out = set()
        if os.path.isdir(d):
            for fn in os.listdir(d):
                if not fn.endswith(".json") or ".corrupt." in fn:
                    continue
                out.add(_decode_name(fn[: -len(".json")]))
        with self._mem_lock:
            out.update(self._mem.get(namespace, {}).keys())
        return sorted(out)

    def delete_namespace(self, namespace: str) -> None:
        import shutil

        d = self._ns_dir(namespace)
        with self._exclusive():
            self._check_fence()
            self._journal.append({"op": "delete_ns", "ns": namespace})
            self.metrics.meter("durability.journalAppends").mark()
            if os.path.isdir(d):
                shutil.rmtree(d)
            prefix = namespace + "/"
            with self._mem_lock:
                for ns in [
                    n for n in self._mem if n == namespace or n.startswith(prefix)
                ]:
                    del self._mem[ns]
            self._maybe_snapshot()

    def close(self) -> None:
        self._journal.close()
