"""Durable controller property store.

The reference keeps all cluster metadata — schemas, table configs,
ideal states, per-segment ZK metadata (incl. LLC offset checkpoints) —
in the ZooKeeper property store
(``PinotHelixResourceManager.java:103``, ``pinot-common/.../metadata/``),
so a controller restart recovers the whole cluster from ZK.  This is
the single-controller analog: one JSON file per record under the
controller's data dir, written atomically (tmp + rename) so a crash
mid-write can never corrupt a record.

Namespaces:
  schemas/<name>.json          Schema.to_json()
  tables/<physical>.json       TableConfig.to_json()
  idealstates/<physical>.json  {segment -> {server -> target state}}
  segments/<physical>/<segment>.json  segment record: metadata +
                               download dir + realtime partition/offset
  streams/<physical>.json      stream-provider descriptor for realtime
                               tables (so consumption resumes)
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional

from pinot_tpu.utils.fileio import atomic_write

_SAFE = "-_"  # NOT '.', or a '..' component would survive encoding


def _encode_key(key: str) -> str:
    """Filesystem-safe record name (segment names contain '__', table
    names are alnum+underscore; escape anything else)."""
    out = []
    for ch in key:
        if ch.isalnum() or ch in _SAFE or ch == "_":
            out.append(ch)
        else:
            out.append(f"%{ord(ch):02x}")
    return "".join(out) + ".json"


class PropertyStore:
    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir
        self._lock = threading.Lock()
        os.makedirs(base_dir, exist_ok=True)

    def _ns_dir(self, namespace: str) -> str:
        # encode each namespace component too: namespaces embed table
        # names, and a hostile name must not escape the store dir
        parts = [_encode_key(p)[: -len(".json")] for p in namespace.split("/") if p]
        return os.path.join(self.base_dir, *parts)

    def _path(self, namespace: str, key: str) -> str:
        return os.path.join(self._ns_dir(namespace), _encode_key(key))

    def put(self, namespace: str, key: str, record: Dict[str, Any]) -> None:
        path = self._path(namespace, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._lock:
            atomic_write(path, json.dumps(record))

    def get(self, namespace: str, key: str) -> Optional[Dict[str, Any]]:
        path = self._path(namespace, key)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def delete(self, namespace: str, key: str) -> None:
        path = self._path(namespace, key)
        with self._lock:
            if os.path.exists(path):
                os.unlink(path)

    def list_keys(self, namespace: str) -> List[str]:
        d = self._ns_dir(namespace)
        if not os.path.isdir(d):
            return []
        out = []
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".json"):
                continue
            raw = fn[: -len(".json")]
            # reverse of _encode_key
            parts = []
            i = 0
            while i < len(raw):
                if raw[i] == "%" and i + 2 < len(raw) + 1:
                    try:
                        parts.append(chr(int(raw[i + 1 : i + 3], 16)))
                        i += 3
                        continue
                    except ValueError:
                        pass
                parts.append(raw[i])
                i += 1
            out.append("".join(parts))
        return out

    def delete_namespace(self, namespace: str) -> None:
        import shutil

        d = self._ns_dir(namespace)
        with self._lock:
            if os.path.isdir(d):
                shutil.rmtree(d)
