"""Durable controller property store.

The reference keeps all cluster metadata — schemas, table configs,
ideal states, per-segment ZK metadata (incl. LLC offset checkpoints) —
in the ZooKeeper property store
(``PinotHelixResourceManager.java:103``, ``pinot-common/.../metadata/``),
so a controller restart recovers the whole cluster from ZK.  This is
the single-controller analog: one JSON file per record under the
controller's data dir, written atomically (tmp + rename) so a crash
mid-write can never corrupt a record.

Namespaces:
  schemas/<name>.json          Schema.to_json()
  tables/<physical>.json       TableConfig.to_json()
  idealstates/<physical>.json  {segment -> {server -> target state}}
  segments/<physical>/<segment>.json  segment record: metadata +
                               download dir + realtime partition/offset
  streams/<physical>.json      stream-provider descriptor for realtime
                               tables (so consumption resumes)
  cluster/epoch.json           the controller-incarnation fencing token

Epoch fencing (the ZK leader-generation analog): a controller claims
authority at construction by bumping ``cluster/epoch`` and becomes the
store's writer; every subsequent ``put``/``delete`` re-reads the stored
epoch and raises a typed ``StaleEpochError`` when a NEWER incarnation
has claimed the store since — so a partitioned-away or zombie
controller cannot clobber the live one's state (split-brain safety).
A store without a writer epoch (bare/test use) is unfenced.
"""
from __future__ import annotations

import fcntl
import json
import os
import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from pinot_tpu.common.fencing import StaleEpochError
from pinot_tpu.utils.fileio import atomic_write

CLUSTER_NS = "cluster"
EPOCH_KEY = "epoch"
_FENCE_LOCK_FILE = ".fence.lock"  # never matches an encoded record name

_SAFE = "-_"  # NOT '.', or a '..' component would survive encoding


def _encode_key(key: str) -> str:
    """Filesystem-safe record name (segment names contain '__', table
    names are alnum+underscore; escape anything else)."""
    out = []
    for ch in key:
        if ch.isalnum() or ch in _SAFE or ch == "_":
            out.append(ch)
        else:
            out.append(f"%{ord(ch):02x}")
    return "".join(out) + ".json"


class PropertyStore:
    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir
        self._lock = threading.Lock()
        # None = unfenced (bare/test stores); set via claim_epoch()
        self._writer_epoch: Optional[int] = None
        # persistent fence-lock fd (opened on first fenced use): flock
        # is per open-file-description, so one long-lived fd gives
        # cross-PROCESS exclusion without 3 syscalls per write; the
        # thread lock above covers threads sharing this fd
        self._fence_fd = None
        os.makedirs(base_dir, exist_ok=True)

    def _ns_dir(self, namespace: str) -> str:
        # encode each namespace component too: namespaces embed table
        # names, and a hostile name must not escape the store dir
        parts = [_encode_key(p)[: -len(".json")] for p in namespace.split("/") if p]
        return os.path.join(self.base_dir, *parts)

    def _path(self, namespace: str, key: str) -> str:
        return os.path.join(self._ns_dir(namespace), _encode_key(key))

    # -- epoch fencing -------------------------------------------------
    @contextmanager
    def _exclusive(self, force_flock: bool = False):
        """Thread lock + cross-PROCESS file lock over the store: the
        fence check and the write it guards must be one atomic unit, or
        a zombie's in-flight write could land just after a newer
        incarnation claims the store (check-then-act race).  Unfenced
        stores (no claimed epoch — bare/test use) skip the file lock:
        their fence check is a no-op, so the thread lock alone is the
        pre-fencing behavior."""
        with self._lock:
            if self._writer_epoch is None and not force_flock:
                yield
                return
            if self._fence_fd is None:
                self._fence_fd = open(
                    os.path.join(self.base_dir, _FENCE_LOCK_FILE), "a+b"
                )
            fcntl.flock(self._fence_fd, fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(self._fence_fd, fcntl.LOCK_UN)

    def stored_epoch(self) -> int:
        """The incarnation currently holding the store (0 = unclaimed).
        Read from disk every time: the whole point is seeing a NEWER
        claimant that may live in another process."""
        path = self._path(CLUSTER_NS, EPOCH_KEY)
        if not os.path.exists(path):
            return 0
        try:
            with open(path) as f:
                return int(json.load(f).get("epoch", 0))
        except (ValueError, OSError):
            return 0

    @property
    def writer_epoch(self) -> Optional[int]:
        return self._writer_epoch

    def claim_epoch(self) -> int:
        """Claim write authority: bump ``cluster/epoch`` and become the
        store's writer.  Every OLDER incarnation's writes are rejected
        from this moment (their next ``put``/``delete`` raises
        ``StaleEpochError``)."""
        with self._exclusive(force_flock=True):
            epoch = self.stored_epoch() + 1
            path = self._path(CLUSTER_NS, EPOCH_KEY)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write(path, json.dumps({"epoch": epoch}))
            self._writer_epoch = epoch
        return epoch

    def _check_fence(self) -> None:
        if self._writer_epoch is None:
            return
        stored = self.stored_epoch()
        if stored > self._writer_epoch:
            raise StaleEpochError(
                f"property store claimed by epoch {stored}; this writer "
                f"holds stale epoch {self._writer_epoch}",
                stale=self._writer_epoch,
                current=stored,
            )

    def put(self, namespace: str, key: str, record: Dict[str, Any]) -> None:
        path = self._path(namespace, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._exclusive():
            self._check_fence()
            atomic_write(path, json.dumps(record))

    def get(self, namespace: str, key: str) -> Optional[Dict[str, Any]]:
        path = self._path(namespace, key)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def delete(self, namespace: str, key: str) -> None:
        path = self._path(namespace, key)
        with self._exclusive():
            self._check_fence()
            if os.path.exists(path):
                os.unlink(path)

    def list_keys(self, namespace: str) -> List[str]:
        d = self._ns_dir(namespace)
        if not os.path.isdir(d):
            return []
        out = []
        for fn in sorted(os.listdir(d)):
            if not fn.endswith(".json"):
                continue
            raw = fn[: -len(".json")]
            # reverse of _encode_key
            parts = []
            i = 0
            while i < len(raw):
                if raw[i] == "%" and i + 2 < len(raw) + 1:
                    try:
                        parts.append(chr(int(raw[i + 1 : i + 3], 16)))
                        i += 3
                        continue
                    except ValueError:
                        pass
                parts.append(raw[i])
                i += 1
            out.append("".join(parts))
        return out

    def delete_namespace(self, namespace: str) -> None:
        import shutil

        d = self._ns_dir(namespace)
        with self._exclusive():
            self._check_fence()
            if os.path.isdir(d):
                shutil.rmtree(d)
