from pinot_tpu.controller.resource_manager import ClusterResourceManager, InstanceState
from pinot_tpu.controller.store import SegmentStore
from pinot_tpu.controller.managers import RetentionManager, ValidationManager, SegmentStatusChecker
from pinot_tpu.controller.controller import Controller, ControllerHttpServer

__all__ = [
    "ClusterResourceManager",
    "InstanceState",
    "SegmentStore",
    "RetentionManager",
    "ValidationManager",
    "SegmentStatusChecker",
    "Controller",
    "ControllerHttpServer",
]
