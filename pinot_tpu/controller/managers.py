"""Controller background managers.

Reference counterparts:
- RetentionManager (``helix/core/retention/RetentionManager.java:50``):
  periodically deletes segments whose end time is past the table's
  retention window.
- ValidationManager (``validation/ValidationManager.java:64``): compares
  ideal vs external view, retries ERROR partitions, emits
  missing-segment metrics (and, for realtime tables, re-creates missing
  consuming segments — see ``pinot_tpu.realtime``).
- SegmentStatusChecker (``helix/SegmentStatusChecker.java``): gauges of
  segments in ERROR / missing replicas.

Managers are explicit ``run_once()`` steps driven by a thread loop (or
tests calling run_once directly — deterministic, no sleeps).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from pinot_tpu.common.schema import time_unit_to_millis
from pinot_tpu.controller.resource_manager import ClusterResourceManager, ERROR, ONLINE
from pinot_tpu.utils.metrics import ControllerMetrics

logger = logging.getLogger(__name__)


class _PeriodicManager:
    def __init__(self, interval_s: float) -> None:
        self.interval_s = interval_s
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def run_once(self) -> None:
        raise NotImplementedError

    def start(self) -> None:
        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:
                    logger.exception("%s run failed", type(self).__name__)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


class RetentionManager(_PeriodicManager):
    def __init__(
        self,
        resources: ClusterResourceManager,
        store,
        interval_s: float = 3600.0,
        now_ms=None,
    ) -> None:
        super().__init__(interval_s)
        self.resources = resources
        self.store = store
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))

    def run_once(self) -> None:
        now = self._now_ms()
        for table in self.resources.tables():
            config = self.resources.table_configs.get(table)
            if config is None or config.retention.retention_time_value <= 0:
                continue
            window_ms = config.retention.retention_time_value * time_unit_to_millis(
                config.retention.retention_time_unit
            )
            for seg in self.resources.segments_of(table):
                info = self.resources.get_segment_metadata(table, seg)
                if not info:
                    continue
                meta = info.get("metadata")
                if meta is None or meta.end_time is None or meta.time_column is None:
                    continue
                end_ms = meta.end_time * time_unit_to_millis(meta.time_unit)
                if end_ms < now - window_ms:
                    logger.info("retention: deleting %s/%s", table, seg)
                    self.resources.delete_segment(table, seg)
                    if self.store is not None:
                        self.store.delete(table, seg)


class ValidationManager(_PeriodicManager):
    def __init__(self, resources: ClusterResourceManager, interval_s: float = 300.0) -> None:
        super().__init__(interval_s)
        self.resources = resources
        self.metrics = ControllerMetrics("validation")
        self.realtime_manager = None  # wired by realtime coordinator (stage 7)

    def run_once(self) -> None:
        for table in self.resources.tables():
            ideal = self.resources.get_ideal_state(table)
            view = self.resources.get_external_view(table)
            missing = 0
            errors = 0
            for seg, replicas in ideal.items():
                actual = view.get(seg, {})
                for server, target in replicas.items():
                    got = actual.get(server)
                    if got == ERROR:
                        errors += 1
                        self.resources.reset_segment(table, seg, server)
                    elif got != target:
                        missing += 1
                        self.resources.reset_segment(table, seg, server)
            self.metrics.gauge(f"{table}.missingReplicas").set(missing)
            self.metrics.gauge(f"{table}.errorReplicas").set(errors)
        if self.realtime_manager is not None:
            self.realtime_manager.ensure_consuming_segments()


class SegmentStatusChecker(_PeriodicManager):
    def __init__(self, resources: ClusterResourceManager, interval_s: float = 300.0) -> None:
        super().__init__(interval_s)
        self.resources = resources
        self.metrics = ControllerMetrics("segmentStatus")

    def run_once(self) -> None:
        for table in self.resources.tables():
            ideal = self.resources.get_ideal_state(table)
            view = self.resources.get_external_view(table)
            total = len(ideal)
            online = sum(
                1
                for seg, replicas in ideal.items()
                if any(view.get(seg, {}).get(s) == replicas[s] for s in replicas)
            )
            pct = 100.0 if total == 0 else 100.0 * online / total
            self.metrics.gauge(f"{table}.percentSegmentsAvailable").set(round(pct, 1))
            self.metrics.gauge(f"{table}.segmentCount").set(total)
