"""Controller background managers.

Reference counterparts:
- RetentionManager (``helix/core/retention/RetentionManager.java:50``):
  periodically deletes segments whose end time is past the table's
  retention window.
- ValidationManager (``validation/ValidationManager.java:64``): compares
  ideal vs external view, retries ERROR partitions, emits
  missing-segment metrics (and, for realtime tables, re-creates missing
  consuming segments — see ``pinot_tpu.realtime``).
- SegmentStatusChecker (``helix/SegmentStatusChecker.java``): gauges of
  segments in ERROR / missing replicas.

Managers are explicit ``run_once()`` steps driven by a thread loop (or
tests calling run_once directly — deterministic, no sleeps).
"""
from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

from pinot_tpu.common.schema import time_unit_to_millis
from pinot_tpu.controller.resource_manager import (
    CONSUMING,
    ClusterResourceManager,
    ERROR,
    ONLINE,
)
from pinot_tpu.utils.metrics import ControllerMetrics

logger = logging.getLogger(__name__)


# every started manager registers here so the conftest thread-leak
# guard can assert that a stopped manager's worker actually exited
# (mirrors engine.dispatch._all_lanes / leaked_lane_threads)
_all_managers: "weakref.WeakSet[_PeriodicManager]" = weakref.WeakSet()


class _PeriodicManager:
    def __init__(self, interval_s: float, metrics_scope: Optional[str] = None) -> None:
        self.interval_s = interval_s
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.metrics = ControllerMetrics(metrics_scope or type(self).__name__)
        _all_managers.add(self)

    def run_once(self) -> None:
        raise NotImplementedError

    def start(self) -> None:
        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:
                    # counted, not only logged: a manager silently
                    # failing every round (retention never deleting,
                    # stabilizer never healing) must show on a meter
                    self.metrics.meter(
                        f"manager.{type(self).__name__}.failures"
                    ).mark()
                    logger.exception("%s run failed", type(self).__name__)

        self._thread = threading.Thread(
            target=loop, name=f"manager-{type(self).__name__}", daemon=True
        )
        self._thread.start()

    def stop(self, join_timeout_s: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            # bounded join: the worker is at most one run_once away from
            # seeing the stop event; a wedged run must not hang shutdown
            t.join(timeout=join_timeout_s)


def leaked_manager_threads(grace_s: float = 2.0) -> List[threading.Thread]:
    """Worker threads still alive on STOPPED managers — the post-test
    leak check (running managers, e.g. module-scoped fixtures, are
    exempt: they are still on duty)."""
    suspects: List[threading.Thread] = []
    for mgr in list(_all_managers):
        t = mgr._thread
        if mgr._stop.is_set() and t is not None and t.is_alive():
            suspects.append(t)
    deadline = time.monotonic() + grace_s
    leaked = []
    for t in suspects:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            leaked.append(t)
    return leaked


class RetentionManager(_PeriodicManager):
    def __init__(
        self,
        resources: ClusterResourceManager,
        store,
        interval_s: float = 3600.0,
        now_ms=None,
    ) -> None:
        super().__init__(interval_s, metrics_scope="retention")
        self.resources = resources
        self.store = store
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))

    def run_once(self) -> None:
        now = self._now_ms()
        for table in self.resources.tables():
            config = self.resources.table_configs.get(table)
            if config is None or config.retention.retention_time_value <= 0:
                continue
            window_ms = config.retention.retention_time_value * time_unit_to_millis(
                config.retention.retention_time_unit
            )
            for seg in self.resources.segments_of(table):
                info = self.resources.get_segment_metadata(table, seg)
                if not info:
                    continue
                meta = info.get("metadata")
                if meta is None or meta.end_time is None or meta.time_column is None:
                    continue
                end_ms = meta.end_time * time_unit_to_millis(meta.time_unit)
                if end_ms < now - window_ms:
                    logger.info("retention: deleting %s/%s", table, seg)
                    self.resources.delete_segment(table, seg)
                    if self.store is not None:
                        self.store.delete(table, seg)


class ValidationManager(_PeriodicManager):
    def __init__(
        self,
        resources: ClusterResourceManager,
        interval_s: float = 300.0,
        realtime_manager=None,
    ) -> None:
        super().__init__(interval_s, metrics_scope="validation")
        self.resources = resources
        # RealtimeSegmentManager: every run also re-creates missing
        # CONSUMING segments (the LLC repair half of the reference's
        # ValidationManager); the Controller wires it at construction
        self.realtime_manager = realtime_manager

    def run_once(self) -> None:
        for table in self.resources.tables():
            ideal = self.resources.get_ideal_state(table)
            view = self.resources.get_external_view(table)
            missing = 0
            errors = 0
            for seg, replicas in ideal.items():
                actual = view.get(seg, {})
                for server, target in replicas.items():
                    got = actual.get(server)
                    if got == ERROR:
                        errors += 1
                        self.resources.reset_segment(table, seg, server)
                    elif got != target:
                        missing += 1
                        self.resources.reset_segment(table, seg, server)
            self.metrics.gauge(f"{table}.missingReplicas").set(missing)
            self.metrics.gauge(f"{table}.errorReplicas").set(errors)
        if self.realtime_manager is not None:
            self.realtime_manager.ensure_consuming_segments()


class CrcAuditManager(_PeriodicManager):
    """Cross-replica checksum sweep (ISSUE 19, the control-plane half of
    the audit plane): every round pulls each alive server's claimed
    segment CRCs (``/debug/segments``) and compares the replica sets —
    against each other AND against the property-store metadata CRC the
    segment was registered with.  A disagreement means replicas of the
    same immutable segment serve different bytes (torn download, bit
    rot, a stale copy a failed refresh left behind) — the divergence
    class the per-query shadow auditor cannot see because a broker
    normally scatters each segment to exactly one replica.

    Consuming mutable segments carry no CRC claim and are skipped; a
    server with no admin URL (in-process deployments) is skipped and
    counted, never treated as divergent.  The fetch is pluggable
    (``crc_fn(name, url) -> {table: {segment: crc}}``) so tests drive
    the sweep deterministically without HTTP."""

    def __init__(
        self,
        resources: ClusterResourceManager,
        interval_s: float = 300.0,
        crc_fn=None,
        timeout_s: float = 3.0,
    ) -> None:
        super().__init__(interval_s, metrics_scope="crcAudit")
        self.resources = resources
        self.crc_fn = crc_fn or self._http_crcs
        self.timeout_s = timeout_s
        self._rollup_lock = threading.Lock()
        self._last: Dict = {"runs": 0, "segmentsChecked": 0, "mismatches": []}
        # pre-registered so the sweep plane shows zeros before round one
        for m in (
            "audit.sweep.runs",
            "audit.sweep.segmentsChecked",
            "audit.sweep.skippedInstances",
        ):
            self.metrics.meter(m)
        self.metrics.gauge("audit.crcMismatches").set(0)

    def _http_crcs(self, name: str, url: str) -> Dict:
        import json
        import urllib.request

        with urllib.request.urlopen(
            url.rstrip("/") + "/debug/segments", timeout=self.timeout_s
        ) as resp:
            return json.loads(resp.read().decode()).get("segments", {})

    def run_once(self) -> None:
        per_server: Dict[str, Dict] = {}
        skipped = 0
        for inst in self.resources.instances_snapshot():
            if inst.role != "server" or not inst.alive:
                continue
            if not inst.url:
                skipped += 1
                continue
            try:
                per_server[inst.name] = self.crc_fn(inst.name, inst.url) or {}
            except Exception:
                skipped += 1
        checked = 0
        mismatches: List[Dict] = []
        for table in self.resources.tables():
            ideal = self.resources.get_ideal_state(table)
            for seg, replicas in ideal.items():
                crcs = {
                    server: per_server[server][table][seg]
                    for server in replicas
                    if per_server.get(server, {}).get(table, {}).get(seg)
                    is not None
                }
                if not crcs:
                    continue
                checked += 1
                info = self.resources.get_segment_metadata(table, seg) or {}
                expected = getattr(info.get("metadata"), "crc", None)
                vals = set(crcs.values())
                if len(vals) > 1 or (
                    expected is not None and vals != {expected}
                ):
                    mismatches.append(
                        {
                            "table": table,
                            "segment": seg,
                            "expectedCrc": expected,
                            "replicaCrcs": dict(crcs),
                        }
                    )
        self.metrics.meter("audit.sweep.runs").mark()
        self.metrics.meter("audit.sweep.segmentsChecked").mark(checked)
        if skipped:
            self.metrics.meter("audit.sweep.skippedInstances").mark(skipped)
        self.metrics.gauge("audit.crcMismatches").set(len(mismatches))
        with self._rollup_lock:
            self._last = {
                "runs": self._last["runs"] + 1,
                "segmentsChecked": checked,
                "skippedInstances": skipped,
                "serversPolled": sorted(per_server),
                "mismatches": mismatches,
            }

    def snapshot(self) -> Dict:
        """Latest sweep rollup (the controller's ``/debug/audit``)."""
        with self._rollup_lock:
            out = dict(self._last)
        out["intervalS"] = self.interval_s
        return out


class DeepStoreScrubber(_PeriodicManager):
    """Background re-verification of the controller's durable segment
    copies, with reverse replication for lost/corrupt ones.

    The reference's deep store (NFS/HDFS) has storage-level redundancy
    and ``RetentionManager``-adjacent validators; our controller-local
    ``SegmentStore`` is a single copy that nobody reads between upload
    and the next server fetch — bit rot there is invisible until a
    replica tries to load it.  This manager (a ``CrcAuditManager``
    sibling) walks the store on a cadence, re-verifies each copy's CRC
    under the shared ``SamplerBudget`` (scrubbing must never starve
    serving), and repairs a bad copy *from a live server's verified
    local copy* — the reverse of the normal fetch direction, possible
    because servers CRC-verify every segment they load.

    Servers also push suspects: a fetch that fails CRC against the
    store copy reports it here (``report_suspect``), so a rotten copy
    is repaired on the next round instead of poisoning every future
    replica placement.  The copy fetch is pluggable
    (``copy_fn(name, url, table, segment) -> bytes``) so in-process
    tests drive repairs without HTTP."""

    def __init__(
        self,
        resources: ClusterResourceManager,
        store,
        interval_s: float = 300.0,
        budget=None,
        copy_fn=None,
        timeout_s: float = 10.0,
    ) -> None:
        super().__init__(interval_s, metrics_scope="deepstore")
        from pinot_tpu.utils.audit import BUDGET

        self.resources = resources
        self.store = store
        self.budget = budget if budget is not None else BUDGET
        self.copy_fn = copy_fn or self._http_copy
        self.timeout_s = timeout_s
        self._suspect_lock = threading.Lock()
        self._suspects: List[Dict] = []
        self._rollup_lock = threading.Lock()
        self._last: Dict = {
            "runs": 0,
            "copiesChecked": 0,
            "corruptCopies": 0,
            "repairs": 0,
            "repairFailures": 0,
            "budgetDenied": 0,
            "evidence": [],
        }
        for m in (
            "deepstore.scrub.runs",
            "deepstore.scrub.copiesChecked",
            "deepstore.scrub.budgetDenied",
            "deepstore.corruptCopies",
            "deepstore.repairs",
            "deepstore.repairFailures",
            "deepstore.suspectsReported",
        ):
            self.metrics.meter(m)
        self.metrics.gauge("deepstore.suspectsPending").set(0)

    # -- suspect intake (fetch-path feedback) -------------------------

    def report_suspect(self, table: str, segment: str, source: str = "") -> None:
        """A fetch failed CRC against the store copy: queue that copy
        for priority verification on the next scrub round."""
        with self._suspect_lock:
            if any(
                s["table"] == table and s["segment"] == segment
                for s in self._suspects
            ):
                return
            self._suspects.append(
                {"table": table, "segment": segment, "source": source}
            )
            pending = len(self._suspects)
        self.metrics.meter("deepstore.suspectsReported").mark()
        self.metrics.gauge("deepstore.suspectsPending").set(pending)

    def _http_copy(self, name: str, url: str, table: str, segment: str) -> bytes:
        import urllib.request

        if not url:
            raise RuntimeError(f"server {name} has no admin URL")
        with urllib.request.urlopen(
            url.rstrip("/") + f"/segments/{table}/{segment}/copy",
            timeout=self.timeout_s,
        ) as resp:
            return resp.read()

    # -- scrub round --------------------------------------------------

    def _targets(self) -> List[Dict]:
        """Suspects first (priority), then the cadence walk over every
        segment the metadata expects a durable copy for (CONSUMING
        realtime segments have none yet)."""
        with self._suspect_lock:
            targets = list(self._suspects)
            self._suspects = []
        seen = {(t["table"], t["segment"]) for t in targets}
        for table in self.resources.tables():
            ideal = self.resources.get_ideal_state(table)
            for seg, replicas in ideal.items():
                if (table, seg) in seen:
                    continue
                if replicas and all(s == CONSUMING for s in replicas.values()):
                    continue
                targets.append({"table": table, "segment": seg, "source": ""})
        return targets

    def run_once(self) -> None:
        checked = 0
        denied = 0
        corrupt: List[Dict] = []
        repaired = 0
        repair_failures = 0
        evidence: List[Dict] = []
        for target in self._targets():
            table, seg = target["table"], target["segment"]
            if not self.budget.take():
                denied += 1
                if target["source"]:
                    # keep a server-reported suspect for the next round
                    # rather than dropping the report on the floor
                    self.report_suspect(table, seg, target["source"])
                continue
            info = self.resources.get_segment_metadata(table, seg) or {}
            expected = getattr(info.get("metadata"), "crc", None)
            try:
                self.store.verify_copy(table, seg, expected_crc=expected)
                checked += 1
                continue
            except FileNotFoundError:
                reason = "missing"
            except Exception as e:
                reason = f"corrupt: {e}"
            checked += 1
            row = {
                "table": table,
                "segment": seg,
                "reason": reason,
                "reportedBy": target["source"] or None,
                "repairedFrom": None,
            }
            corrupt.append(row)
            src = self._repair(table, seg, expected)
            if src:
                row["repairedFrom"] = src
                repaired += 1
            else:
                repair_failures += 1
            evidence.append(row)

        self.metrics.meter("deepstore.scrub.runs").mark()
        self.metrics.meter("deepstore.scrub.copiesChecked").mark(checked)
        if denied:
            self.metrics.meter("deepstore.scrub.budgetDenied").mark(denied)
        if corrupt:
            self.metrics.meter("deepstore.corruptCopies").mark(len(corrupt))
        if repaired:
            self.metrics.meter("deepstore.repairs").mark(repaired)
        if repair_failures:
            self.metrics.meter("deepstore.repairFailures").mark(repair_failures)
        with self._suspect_lock:
            pending = len(self._suspects)
        self.metrics.gauge("deepstore.suspectsPending").set(pending)
        with self._rollup_lock:
            self._last = {
                "runs": self._last["runs"] + 1,
                "copiesChecked": checked,
                "corruptCopies": len(corrupt),
                "repairs": self._last["repairs"] + repaired,
                "repairFailures": repair_failures,
                "budgetDenied": denied,
                "evidence": (self._last["evidence"] + evidence)[-32:],
            }

    def _repair(self, table: str, seg: str, expected_crc) -> Optional[str]:
        """Reverse replication: pull verified bytes from a live ONLINE
        replica, re-verify them independently, and install as the new
        durable copy.  Returns the donor server name or None."""
        import tempfile

        from pinot_tpu.segment.format import read_segment, verify_segment_crc

        view = self.resources.get_external_view(table).get(seg, {})
        urls = {
            inst.name: inst.url
            for inst in self.resources.instances_snapshot()
            if inst.role == "server" and inst.alive
        }
        for server, state in sorted(view.items()):
            if state != ONLINE or server not in urls:
                continue
            try:
                data = self.copy_fn(server, urls[server], table, seg)
                if not data:
                    continue
                # verify the donated bytes before trusting them: parse,
                # recompute the data CRC, and match the registered crc
                with tempfile.TemporaryDirectory() as td:
                    fpath = os.path.join(td, "columns.pnt")
                    with open(fpath, "wb") as f:
                        f.write(data)
                    donated = read_segment(fpath)
                    verify_segment_crc(donated, source=f"repair:{server}")
                    if (
                        expected_crc
                        and donated.metadata.crc
                        and donated.metadata.custom.get("dataCrc")
                        and int(donated.metadata.crc) != int(expected_crc)
                    ):
                        continue
                self.store.save_bytes(table, seg, data)
                self.store.verify_copy(table, seg, expected_crc=expected_crc)
                return server
            except Exception:
                logger.exception(
                    "deep-store repair of %s/%s from %s failed", table, seg, server
                )
        return None

    def snapshot(self) -> Dict:
        """Latest scrub rollup (the controller's ``/debug/deepstore``)."""
        with self._rollup_lock:
            out = dict(self._last)
        with self._suspect_lock:
            out["suspectsPending"] = len(self._suspects)
        out["intervalS"] = self.interval_s
        return out


class SegmentStatusChecker(_PeriodicManager):
    def __init__(self, resources: ClusterResourceManager, interval_s: float = 300.0) -> None:
        super().__init__(interval_s, metrics_scope="segmentStatus")
        self.resources = resources

    def run_once(self) -> None:
        for table in self.resources.tables():
            ideal = self.resources.get_ideal_state(table)
            view = self.resources.get_external_view(table)
            total = len(ideal)
            online = sum(
                1
                for seg, replicas in ideal.items()
                if any(view.get(seg, {}).get(s) == replicas[s] for s in replicas)
            )
            pct = 100.0 if total == 0 else 100.0 * online / total
            self.metrics.gauge(f"{table}.percentSegmentsAvailable").set(round(pct, 1))
            self.metrics.gauge(f"{table}.segmentCount").set(total)
