"""SelfStabilizer: failure-driven convergence of ideal state onto the
live cluster.

The reference control plane (Helix full-auto rebalancer +
``ValidationManager``) continuously converges the external view toward
the ideal state, so replicas on a dead server are re-hosted without an
operator.  The heartbeat expiry in ``controller/network.py`` only
*hides* a dead server from routing; every replica it held would stay
lost until a human called ``rebalance_table``.  This manager closes the
loop:

- **Detect** under-replicated segments: replicas on dead (or
  unregistered) and draining servers do not count against the table's
  target replication.
- **Grace window** (``PINOT_TPU_STABILIZE_GRACE_S``): a server's death
  only becomes actionable after the window, so a GC pause or a rolling
  bounce never triggers mass data movement.  Draining is deliberate
  operator intent and gets no grace.
- **Re-replicate** onto live tenant servers, least-loaded first with
  load measured in DOCS (not segment count), so placement stays
  balanced under skewed segment sizes (the skew-resistant-placement
  idea from PIM-tree, PAPERS.md).  The new replica is driven ONLINE
  through the normal transition path and re-fetches the segment from
  the controller's durable store copy.
- **Clean up** one round later: once the external view shows the
  target number of live ONLINE replicas, the dead/draining replicas
  drop out of the ideal state (DROPPED is sent only to live holders).
- **CONSUMING segments** are never copied (a consumer's rows are not
  durable): when every holder is unavailable the segment is retired and
  handed to ``RealtimeSegmentManager.ensure_consuming_segments``, which
  re-creates it on a live server resuming from the last COMMITTED
  offset.

Every action is a persisted ideal-state write, so the whole plan is
crash-idempotent: a controller killed mid-round recovers the
partially-applied ideal state from the property store and the next
round converges to the same fixpoint (add-phase is keyed on deficits,
drop-phase on restored coverage — both derived, never remembered).

**Proactive skew-aware rebalancing (r15).**  Healing only ever reacted
to death; at fleet breadth the killer is *skew* — a hot tenant's
doc-heavy, cost-heavy segments concentrating on one server while the
rest idle (the placement half of the PIM-tree / JSPIM skew argument:
skew-resistant placement, not just skew-aware kernels, keeps tails
flat).  Each round the planner:

- weighs every server's load as **docs x cost-rate**: segment docs
  (the capacity axis ``/debug/capacity`` reports) scaled by the
  table's recent scan rate (the ``cost.*`` attribution the brokers
  publish), with an optional per-server busy-fraction tiebreak from
  ``/debug/utilization`` — both wired through pluggable providers so
  the in-process harness can weigh without HTTP;
- applies **hysteresis**: the per-tenant max/mean load ratio must
  exceed ``PINOT_TPU_REBALANCE_SKEW_RATIO`` for
  ``PINOT_TPU_REBALANCE_HYSTERESIS`` consecutive rounds before
  anything moves — one hot minute moves nothing;
- plans at most ``PINOT_TPU_REBALANCE_MAX_MOVES`` moves per round,
  each **make-before-break**: phase 1 adds the replica on the cold
  server (fetched + CRC-verified + driven ONLINE through the normal
  transition path); phase 2 — a LATER round — drops the hot replica
  only after the external view proves the segment still has
  target-many live ONLINE replicas without it.  Routing covers never
  lose the segment mid-move, so the acceptance bar is zero failed
  queries, not best-effort.
- phase 2 is **derived, never remembered**: any segment with more
  replicas than target trims its most-loaded coverage-safe replica
  (an ERROR destination aborts the move instead), so a controller
  crash between the phases recovers the surplus from the property
  store and converges identically.

CONSUMING segments are never rebalanced (a consumer's rows are not
durable); rebalancing yields entirely while servers are dead, draining,
or any segment is under-replicated — healing always wins the round.
"""
from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from pinot_tpu.common.conf import env_float as _env_float
from pinot_tpu.controller.managers import _PeriodicManager
from pinot_tpu.controller.resource_manager import (
    CONSUMING,
    ClusterResourceManager,
    ERROR,
    ONLINE,
)

logger = logging.getLogger(__name__)

_EVENT_RING = 256


class SelfStabilizer(_PeriodicManager):
    def __init__(
        self,
        resources: ClusterResourceManager,
        realtime_manager=None,
        interval_s: float = 2.0,
        grace_s: Optional[float] = None,
        now=None,
    ) -> None:
        super().__init__(interval_s, metrics_scope="stabilizer")
        self.resources = resources
        self.realtime_manager = realtime_manager
        if grace_s is None:
            grace_s = float(os.environ.get("PINOT_TPU_STABILIZE_GRACE_S", "5"))
        self.grace_s = grace_s
        self._now = now or time.monotonic
        # first-observed-dead timestamps; entries clear on recovery
        self._dead_since: Dict[str, float] = {}
        # heal/rebalance event ring for /debug/stabilizer and the
        # dashboard (the controller-side analog of the server's
        # selfHealing counters); every event carries a "class" field —
        # "heal" (failure-driven) vs "rebalance" (skew-driven) — so an
        # operator reading the ring can tell repair from optimization
        self._events: Deque[Dict[str, Any]] = deque(maxlen=_EVENT_RING)
        # -- proactive skew-aware rebalance knobs (r15) -----------------
        self.rebalance_enabled = os.environ.get("PINOT_TPU_REBALANCE", "1") != "0"
        # per-tenant max/mean doc-x-cost load ratio that counts as skew
        self.rebalance_skew_ratio = _env_float("PINOT_TPU_REBALANCE_SKEW_RATIO", 2.0)
        # consecutive skewed evaluations before anything moves
        self.rebalance_hysteresis = int(
            _env_float("PINOT_TPU_REBALANCE_HYSTERESIS", 3)
        )
        # phase-1 move starts per round, cluster-wide
        self.rebalance_max_moves = int(
            _env_float("PINOT_TPU_REBALANCE_MAX_MOVES", 2)
        )
        # pluggable skew inputs (wired by the Controller to TTL-cached
        # /debug/capacity + /debug/utilization rollups; None = docs-only
        # weighting, which is what in-process harnesses get):
        #   cost_rate_fn() -> {raw table name: docsScanned rate1m}
        #   busy_fn()      -> {server name: busyFraction in [0, 1]}
        self.cost_rate_fn = None
        self.busy_fn = None
        # pluggable tier pressure (r18, wired by the Controller to the
        # /debug/capacity residency section; None = no memory-pressure
        # weighting):  pressure_fn() -> {server name: hot/cap in [0, 1]}
        # — a server whose hot tier is pinned against its HBM cap has
        # its placement load inflated up to 2x, so the planner moves
        # segments OFF it before allocation failures start healing
        self.pressure_fn = None
        # pluggable warm-start readiness (wired by the Controller to the
        # heartbeat-reported warming flags; None = everyone ready, the
        # pre-r16 behavior):  readiness_fn(server name) -> bool
        self.readiness_fn = None
        # (table, segment) -> monotonic stamp of the FIRST readiness
        # deferral: a destination that never finishes prewarming can
        # only hold a trim for the prewarm window, never forever
        self.prewarm_timeout_s = _env_float("PINOT_TPU_PREWARM_TIMEOUT_S", 30.0)
        self._warm_waits: Dict[Tuple[str, str], float] = {}
        self._skew_rounds: Dict[str, int] = {}  # tenant -> consecutive
        # (table, segment) -> {"src", "dst"}: observability for
        # in-flight make-before-break moves.  NOT load-bearing — the
        # trim phase derives surplus from ideal state vs view, so a
        # restart that loses this map still completes every move.
        self._pending_moves: Dict[Tuple[str, str], Dict[str, str]] = {}
        for m in (
            "stabilizer.rounds",
            "stabilizer.replicasAdded",
            "stabilizer.replicasDropped",
            "stabilizer.consumingReassigned",
            "stabilizer.graceDeferrals",
            "stabilizer.leaseDeferrals",
            "rebalance.evaluations",
            "rebalance.skewDeferrals",
            "rebalance.movesStarted",
            "rebalance.movesCompleted",
            "rebalance.movesAborted",
            "rebalance.prewarmDeferrals",
        ):
            self.metrics.meter(m)
        for g in (
            "stabilizer.underReplicatedSegments",
            "stabilizer.drainingInstances",
            "stabilizer.deadServers",
            "rebalance.pendingMoves",
            "rebalance.imbalanceRatio",
        ):
            self.metrics.gauge(g).set(0)

    # -- observability --------------------------------------------------
    def _event(self, kind: str, cls: str = "heal", **fields: Any) -> None:
        self._events.append(
            {"tsMs": int(time.time() * 1000), "event": kind, "class": cls, **fields}
        )

    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def debug_snapshot(self) -> Dict[str, Any]:
        now = self._now()
        return {
            "graceSeconds": self.grace_s,
            "deadTracked": {
                name: round(now - since, 3)
                for name, since in sorted(self._dead_since.items())
            },
            "rebalance": {
                "enabled": self.rebalance_enabled,
                "skewRatio": self.rebalance_skew_ratio,
                "hysteresisRounds": self.rebalance_hysteresis,
                "maxMovesPerRound": self.rebalance_max_moves,
                "skewRounds": dict(self._skew_rounds),
                "pendingMoves": [
                    {"table": t, "segment": s, **info}
                    for (t, s), info in sorted(self._pending_moves.items())
                ],
                "prewarmTimeoutS": self.prewarm_timeout_s,
                "warmWaits": {
                    f"{t}/{s}": round(now - since, 3)
                    for (t, s), since in sorted(self._warm_waits.items())
                },
            },
            "events": self.events(),
            "metrics": self.metrics.snapshot(),
        }

    def _quiescent(self, healthy, draining, server_state) -> bool:
        """Cheap precheck: True when no round work can possibly exist —
        nobody draining, every ideal-state replica sits on a healthy
        server, and every non-consuming segment meets its target
        replication.  One lock hold, no copies, no metadata reads."""
        if draining:
            return False
        res = self.resources
        with res._lock:
            for table, ideal in res.ideal_states.items():
                config = res.table_configs.get(table)
                if config is None or not ideal:
                    continue
                n_eligible = sum(
                    1
                    for s in healthy
                    if config.server_tenant in server_state[s][2]
                )
                n_target = min(config.replication, n_eligible)
                for replicas in ideal.values():
                    if not set(replicas) <= healthy:
                        return False
                    if CONSUMING in replicas.values():
                        continue
                    if len(replicas) < n_target:
                        return False  # under-replicated: heal must run
                    if self.rebalance_enabled and len(replicas) > n_target:
                        # over-replicated: make-before-break phase 2
                        # pending (with the kill switch set, a frozen
                        # surplus must not defeat the cheap steady path)
                        return False
        return True

    # -- the convergence round -----------------------------------------
    def run_once(self) -> None:
        res = self.resources
        now = self._now()
        self.metrics.meter("stabilizer.rounds").mark()
        with res._lock:
            server_state = {
                n: (i.alive, i.draining, set(i.tags))
                for n, i in res.instances.items()
                if i.role == "server"
            }
            lease_until = {
                n: i.lease_until
                for n, i in res.instances.items()
                if i.role == "server"
            }
        healthy = {n for n, (a, d, _) in server_state.items() if a and not d}
        draining = {n for n, (a, d, _) in server_state.items() if a and d}

        def is_dead(s: str) -> bool:
            st = server_state.get(s)
            return st is None or not st[0]

        _actionable: Dict[str, bool] = {}

        def actionable_dead(s: str) -> bool:
            """Dead AND past the grace window (tracking starts at first
            observation, so a controller restarted mid-outage re-waits
            the window rather than acting on a stale clock) AND past its
            serving lease — a heartbeat-missing server whose lease has
            not expired may be alive-but-partitioned and still serving,
            so replicas move only after the lease window, never on a
            single missed heartbeat.  Memoized per round: the deferral
            meters count servers, not replicas."""
            if s in _actionable:
                return _actionable[s]
            if not is_dead(s):
                _actionable[s] = False
                return False
            since = self._dead_since.setdefault(s, now)
            if since == now:
                self._event("serverDead", server=s)
            ok = now - since >= self.grace_s
            if not ok:
                self.metrics.meter("stabilizer.graceDeferrals").mark()
            else:
                until = lease_until.get(s)
                if until is not None and now < until:
                    # lease fence: confirmed-dead is "lease expired";
                    # until then this is only "unreachable from here"
                    ok = False
                    self.metrics.meter("stabilizer.leaseDeferrals").mark()
                    self._event(
                        "leaseDeferral", server=s,
                        remainingS=round(until - now, 3),
                    )
            _actionable[s] = ok
            return ok

        # recoveries clear the death clock (a flap restarts the window)
        for s in [s for s in self._dead_since if not is_dead(s)]:
            del self._dead_since[s]
            self._event("serverRecovered", server=s)

        if self._quiescent(healthy, draining, server_state):
            # steady state: one lock hold over replica-set keys, no view
            # copies, no per-segment metadata reads — the 2s background
            # cadence must not contend with the serving path for nothing
            self.metrics.gauge("stabilizer.underReplicatedSegments").set(0)
            self.metrics.gauge("stabilizer.drainingInstances").set(0)
            self.metrics.gauge("stabilizer.deadServers").set(len(self._dead_since))
            # a healthy, fully-replicated cluster is EXACTLY when
            # proactive rebalancing is allowed to look for skew
            if self.rebalance_enabled and not self._dead_since:
                self._rebalance_tick(healthy, server_state)
            return

        under_replicated = 0
        consuming_repair = False
        for table in res.tables():
            config = res.table_configs.get(table)
            if config is None:
                continue
            eligible = sorted(
                s for s in healthy if config.server_tenant in server_state[s][2]
            )
            ideal = res.get_ideal_state(table)
            if not ideal:
                continue
            n_target = min(config.replication, len(eligible))
            # doc-weighted load: a server holding one huge segment is
            # "fuller" than one holding three tiny ones (skew-resistant
            # placement) — counted over the ideal state incl. this
            # round's own additions
            def weight(seg: str) -> int:
                info = res.get_segment_metadata(table, seg)
                meta = info.get("metadata") if info else None
                docs = getattr(meta, "num_docs", 0) if meta is not None else 0
                return max(1, int(docs or 0))

            load = {s: 0 for s in eligible}
            for seg, replicas in ideal.items():
                w = weight(seg)
                for s in replicas:
                    if s in load:
                        load[s] += w
            view = res.get_external_view(table)
            for seg in sorted(ideal):
                replicas = ideal[seg]
                unavailable = [
                    s for s in replicas if s in draining or actionable_dead(s)
                ]
                if CONSUMING in replicas.values():
                    # a consumer's rows are not durable — never copy the
                    # segment; if NO holder is serving it, retire it so
                    # ensure_consuming_segments re-creates it on a live
                    # server at the last committed offset
                    if replicas and not (set(replicas) & healthy) and len(
                        unavailable
                    ) == len(replicas):
                        if self.realtime_manager is not None:
                            self.realtime_manager.release_segment_consumers(seg)
                        held = res.retire_segment(table, seg)
                        consuming_repair = True
                        self.metrics.meter("stabilizer.consumingReassigned").mark()
                        self._event(
                            "consumingRetired", table=table, segment=seg,
                            servers=held,
                        )
                    elif unavailable:
                        # a healthy holder keeps consuming: shed only the
                        # unavailable replicas (a drain would otherwise
                        # never report drained — the next sequence opens
                        # at full replication on live servers at commit).
                        # Transiently under-replicated, as the
                        # reference's fixed consuming assignment is too.
                        for s in unavailable:
                            if self.realtime_manager is not None:
                                self.realtime_manager.release_segment_consumers(
                                    seg, server=s
                                )
                            if res.remove_segment_replica(table, seg, s):
                                self.metrics.meter(
                                    "stabilizer.replicasDropped"
                                ).mark()
                                self._event(
                                    "replicaDropped", table=table, segment=seg,
                                    server=s, consuming=True,
                                    reason="draining" if s in draining else "dead",
                                )
                    continue
                if n_target == 0:
                    under_replicated += 1
                    continue
                # drop phase FIRST, using the pre-round external view: a
                # dead/draining replica leaves the ideal state only after
                # the view proves target-many live replicas serve the
                # segment (so the add phase of round N is confirmed by
                # the view before round N+1 drops anything)
                target_state = next(iter(replicas.values()), ONLINE)
                covered = [
                    s
                    for s, st in view.get(seg, {}).items()
                    if s in healthy and s in replicas and st == target_state
                ]
                if len(covered) >= n_target:
                    for s in unavailable:
                        # readiness gate on DRAINING drops only: a drain
                        # is planned movement, so the replacement cover
                        # should be warm before the old replica leaves.
                        # Dead victims drop immediately — holding a
                        # corpse in the ideal state buys nothing.
                        if s in draining and not self._destinations_ready(
                            table, seg, covered, n_target, victim=s,
                            cls="heal",
                        ):
                            continue
                        if res.remove_segment_replica(table, seg, s):
                            self.metrics.meter("stabilizer.replicasDropped").mark()
                            self._event(
                                "replicaDropped", table=table, segment=seg,
                                server=s, reason="draining" if s in draining else "dead",
                            )
                            replicas.pop(s, None)
                # make-before-break phase 2: a segment with MORE live
                # replicas than target (the rebalance planner's phase-1
                # add, or a surplus left by a crash / replication
                # decrease) trims its most-loaded coverage-safe replica
                # once the view proves the rest serve — derived from
                # state, so a controller restart mid-move converges
                # here.  Gated on the same switch as the planner: the
                # PINOT_TPU_REBALANCE=0 kill switch must freeze ALL
                # rebalance movement, including completing phase 2.
                if self.rebalance_enabled and len(replicas) > n_target:
                    self._trim_surplus(
                        table, seg, replicas, view.get(seg, {}),
                        healthy, load, n_target, target_state, weight(seg),
                    )
                # add phase: replicas within grace still count (that IS
                # the grace: no movement yet), draining/actionable ones
                # do not
                counted = [
                    s
                    for s in replicas
                    if s in healthy or (is_dead(s) and not actionable_dead(s))
                ]
                deficit = n_target - len(counted)
                if deficit <= 0:
                    continue
                under_replicated += 1
                w = weight(seg)
                candidates = [s for s in eligible if s not in replicas]
                for _ in range(deficit):
                    if not candidates:
                        break
                    pick = min(candidates, key=lambda s: (load[s], s))
                    candidates.remove(pick)
                    if res.add_segment_replica(table, seg, pick):
                        load[pick] += w
                        self.metrics.meter("stabilizer.replicasAdded").mark()
                        self._event(
                            "replicaAdded", table=table, segment=seg,
                            server=pick, docs=w,
                        )
        if consuming_repair and self.realtime_manager is not None:
            try:
                self.realtime_manager.ensure_consuming_segments()
            except Exception:
                logger.exception("consuming-segment repair failed")
        self.metrics.gauge("stabilizer.underReplicatedSegments").set(under_replicated)
        self.metrics.gauge("stabilizer.drainingInstances").set(len(draining))
        self.metrics.gauge("stabilizer.deadServers").set(len(self._dead_since))
        if (
            self.rebalance_enabled
            and not draining
            and under_replicated == 0
            and not self._dead_since
        ):
            self._rebalance_tick(healthy, server_state)
        else:
            # healing (or draining) owns the round: skew observed while
            # replicas are being re-homed is transient by construction,
            # so the hysteresis clock restarts once the cluster is whole
            self._skew_rounds.clear()

    # -- warm-start readiness gate (r16) --------------------------------
    def _ready(self, server: str) -> bool:
        if self.readiness_fn is None:
            return True
        try:
            return bool(self.readiness_fn(server))
        except Exception:
            # a broken readiness probe must never freeze movement
            logger.warning("readiness provider failed", exc_info=True)
            return True

    def _destinations_ready(
        self,
        table: str,
        seg: str,
        serving,
        n_target: int,
        victim: Optional[str] = None,
        dst: Optional[str] = None,
        cls: str = "rebalance",
    ) -> bool:
        """True when removing a replica may proceed: at least
        ``n_target`` of the replicas that would carry coverage
        afterwards have finished prewarming (or this (table, segment)'s
        prewarm wait timed out).  A still-warming destination serves
        correctly — it is just slow until its compiles land — so the
        deferral is bounded: the first deferral starts the clock, and
        past ``PINOT_TPU_PREWARM_TIMEOUT_S`` the movement proceeds
        anyway (a wedged prewarm must not pin surplus replicas)."""
        n_ready = sum(1 for s in serving if s != victim and self._ready(s))
        if n_ready >= n_target:
            self._warm_waits.pop((table, seg), None)
            return True
        first = self._warm_waits.setdefault((table, seg), self._now())
        if self._now() - first < self.prewarm_timeout_s:
            self.metrics.meter("rebalance.prewarmDeferrals").mark()
            self._event(
                "rebalanceTrimDeferred", cls=cls, table=table,
                segment=seg, server=victim, dst=dst,
                reason="destination warming",
            )
            return False
        self._warm_waits.pop((table, seg), None)
        self._event(
            "rebalancePrewarmTimeout", cls=cls, table=table,
            segment=seg, server=victim, dst=dst,
        )
        return True

    # -- proactive skew-aware rebalancing (r15) -------------------------
    def _trim_surplus(
        self,
        table: str,
        seg: str,
        replicas: Dict[str, str],
        seg_view: Dict[str, str],
        healthy,
        load: Dict[str, int],
        n_target: int,
        target_state: str,
        w: int,
    ) -> None:
        """Drop surplus replicas of one segment, coverage-first: a
        victim may only leave while the external view still shows
        ``n_target`` live replicas serving WITHOUT it.  An ERROR
        destination aborts that move instead (the fetch/load failed —
        keep the source, drop the wreck)."""
        res = self.resources
        pending = self._pending_moves.get((table, seg), {})

        def covered_without(victim: str) -> bool:
            return (
                sum(
                    1
                    for s in replicas
                    if s != victim
                    and s in healthy
                    and seg_view.get(s) == target_state
                )
                >= n_target
            )

        # abort first: an ERROR replica in a surplus set is a failed
        # phase-1 destination — dropping it cancels the move cleanly.
        # The tenant's hysteresis clock restarts too, so a persistently
        # failing destination is retried once per hysteresis window
        # instead of every round (the validation manager keeps
        # resetting the ERROR replica meanwhile — whichever heals
        # first wins).
        for s in [s for s, st in seg_view.items() if st == ERROR and s in replicas]:
            if len(replicas) <= n_target:
                break
            if res.remove_segment_replica(table, seg, s):
                replicas.pop(s, None)
                self.metrics.meter("rebalance.movesAborted").mark()
                self._event(
                    "rebalanceMoveAborted", cls="rebalance", table=table,
                    segment=seg, server=s, reason="destination ERROR",
                )
                self._skew_rounds.clear()
                if pending.get("dst") == s:
                    self._pending_moves.pop((table, seg), None)
        while len(replicas) > n_target:
            # a victim must ITSELF be serving (healthy + view at target
            # state): a pending destination mid-fetch is never dropped
            # — cancelling a move just because the copy is slow would
            # livelock the planner into add/drop cycles
            candidates = [
                s
                for s in replicas
                if s in healthy
                and seg_view.get(s) == target_state
                and covered_without(s)
            ]
            if not candidates:
                return  # view not converged yet: never break coverage
            # the recorded move source first (most-loaded by intent);
            # otherwise the most-loaded replica — derived, crash-safe
            src = pending.get("src")
            if src in candidates:
                victim = src
            else:
                victim = max(candidates, key=lambda s: (load.get(s, 0), s))
            # readiness gate: the old replica leaves only once enough of
            # the remaining cover has finished prewarming (or the wait
            # timed out) — a make-before-break move must hand traffic to
            # a WARM destination, not a correct-but-cold one
            serving = [
                s
                for s in replicas
                if s in healthy and seg_view.get(s) == target_state
            ]
            if not self._destinations_ready(
                table, seg, serving, n_target,
                victim=victim, dst=pending.get("dst"),
            ):
                return
            if not res.remove_segment_replica(table, seg, victim):
                return
            replicas.pop(victim, None)
            if victim in load:
                load[victim] -= w
            self.metrics.meter("rebalance.movesCompleted").mark()
            self._event(
                "rebalanceMoveCompleted", cls="rebalance", table=table,
                segment=seg, server=victim, docs=w,
                dst=pending.get("dst"),
            )
            self._pending_moves.pop((table, seg), None)

    def _skew_inputs(self):
        """(cost rates by raw table, busy fraction by server, tier
        pressure by server) from the pluggable providers; failures
        degrade to docs-only weighting — a dead rollup must never stall
        the convergence loop."""
        rates: Dict[str, float] = {}
        busy: Dict[str, float] = {}
        pressure: Dict[str, float] = {}
        if self.cost_rate_fn is not None:
            try:
                rates = dict(self.cost_rate_fn() or {})
            except Exception:
                logger.warning("cost-rate provider failed", exc_info=True)
        if self.busy_fn is not None:
            try:
                busy = dict(self.busy_fn() or {})
            except Exception:
                logger.warning("busy-fraction provider failed", exc_info=True)
        if self.pressure_fn is not None:
            try:
                pressure = dict(self.pressure_fn() or {})
            except Exception:
                logger.warning("tier-pressure provider failed", exc_info=True)
        return rates, busy, pressure

    def _rebalance_tick(self, healthy, server_state) -> None:
        """One skew evaluation (+ possibly phase-1 move starts).  Load
        is doc-weighted per replica, scaled by the owning table's
        recent scan cost rate; imbalance is judged per server tenant
        (moves can only happen inside a tenant's eligible set)."""
        res = self.resources
        self.metrics.meter("rebalance.evaluations").mark()
        # sweep stale pending entries (segment/table deleted out from
        # under an in-flight move) so they never starve the budget
        for table, seg in list(self._pending_moves):
            if res.get_ideal_state(table).get(seg) is None:
                self._pending_moves.pop((table, seg), None)
        rates, busy, pressure = self._skew_inputs()
        with res._lock:
            configs = dict(res.table_configs)
        max_rate = max(rates.values()) if rates else 0.0

        def table_factor(config) -> float:
            # docs x cost-rate: a table burning the cluster weighs up
            # to 2x its doc weight, so the planner spreads IT first
            if max_rate <= 0:
                return 1.0
            return 1.0 + rates.get(config.raw_name, 0.0) / max_rate

        tenants: Dict[str, List[str]] = {}
        for table, config in configs.items():
            tenants.setdefault(config.server_tenant, []).append(table)

        worst_ratio = 0.0
        moves_budget = self.rebalance_max_moves - len(self._pending_moves)
        for tenant in sorted(tenants):
            eligible = sorted(
                s for s in healthy if tenant in server_state[s][2]
            )
            if len(eligible) < 2:
                self._skew_rounds.pop(tenant, None)
                continue
            load: Dict[str, float] = {s: 0.0 for s in eligible}
            # (weight, table, seg, replica set): phase-1 candidates
            movable: List[Tuple[float, str, str, set]] = []
            for table in sorted(tenants[tenant]):
                config = configs[table]
                factor = table_factor(config)
                ideal = res.get_ideal_state(table)
                n_target = min(config.replication, len(eligible))
                for seg, replicas in ideal.items():
                    info = res.get_segment_metadata(table, seg)
                    meta = info.get("metadata") if info else None
                    docs = getattr(meta, "num_docs", 0) if meta is not None else 0
                    w = max(1, int(docs or 0)) * factor
                    for s in replicas:
                        if s in load:
                            load[s] += w
                    if (
                        CONSUMING not in replicas.values()
                        and len(replicas) <= n_target
                        and (table, seg) not in self._pending_moves
                    ):
                        movable.append((w, table, seg, set(replicas)))
            # tier pressure (r18): a server running hot against its HBM
            # cap reads as up to 2x its doc-x-cost load, so the planner
            # drains it preemptively — rebalance is the slow, permanent
            # answer to the pressure that demotion absorbs in the moment
            for s in load:
                p = pressure.get(s, 0.0)
                if p > 0:
                    load[s] *= 1.0 + min(1.0, max(0.0, float(p)))
            mean = sum(load.values()) / len(load)
            if mean <= 0:
                self._skew_rounds.pop(tenant, None)
                continue
            ratio = max(load.values()) / mean
            worst_ratio = max(worst_ratio, ratio)
            if ratio < self.rebalance_skew_ratio:
                self._skew_rounds.pop(tenant, None)
                continue
            seen = self._skew_rounds.get(tenant, 0) + 1
            self._skew_rounds[tenant] = seen
            if seen < self.rebalance_hysteresis:
                # hysteresis: one hot minute moves nothing
                self.metrics.meter("rebalance.skewDeferrals").mark()
                self._event(
                    "skewDeferred", cls="rebalance", tenant=tenant,
                    ratio=round(ratio, 3), consecutiveRounds=seen,
                )
                continue
            self._event(
                "skewDetected", cls="rebalance", tenant=tenant,
                ratio=round(ratio, 3), consecutiveRounds=seen,
            )
            moves_budget = self._plan_tenant_moves(
                tenant, eligible, load, busy, movable, moves_budget
            )
        self.metrics.gauge("rebalance.imbalanceRatio").set(round(worst_ratio, 3))
        self.metrics.gauge("rebalance.pendingMoves").set(len(self._pending_moves))

    def _plan_tenant_moves(
        self,
        tenant: str,
        eligible: List[str],
        load: Dict[str, float],
        busy: Dict[str, float],
        movable: List[Tuple[float, str, str, set]],
        budget: int,
    ) -> int:
        """Start bounded make-before-break moves: hottest server ->
        coldest (busy-fraction tiebreak), moving the largest segment
        that does not overshoot half the gap (an overshooting move
        would just invert the skew and oscillate)."""
        res = self.resources
        movable = sorted(movable, key=lambda m: -m[0])
        while budget > 0:
            src = max(eligible, key=lambda s: (load[s], s))
            dst = min(eligible, key=lambda s: (load[s], busy.get(s, 0.0), s))
            gap = load[src] - load[dst]
            if src == dst or gap <= 0:
                return budget
            pick = None
            for i, (w, table, seg, replicas) in enumerate(movable):
                if src in replicas and dst not in replicas and w <= gap / 2:
                    pick = i
                    break
            if pick is None:
                return budget
            w, table, seg, replicas = movable.pop(pick)
            if not res.add_segment_replica(table, seg, dst):
                continue
            self.metrics.meter("rebalance.movesStarted").mark()
            self._event(
                "rebalanceMoveStarted", cls="rebalance", table=table,
                segment=seg, src=src, dst=dst, docs=int(w), tenant=tenant,
            )
            self._pending_moves[(table, seg)] = {"src": src, "dst": dst}
            load[dst] += w  # src keeps its copy until phase 2 trims it
            budget -= 1
        return budget
