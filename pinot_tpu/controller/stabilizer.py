"""SelfStabilizer: failure-driven convergence of ideal state onto the
live cluster.

The reference control plane (Helix full-auto rebalancer +
``ValidationManager``) continuously converges the external view toward
the ideal state, so replicas on a dead server are re-hosted without an
operator.  The heartbeat expiry in ``controller/network.py`` only
*hides* a dead server from routing; every replica it held would stay
lost until a human called ``rebalance_table``.  This manager closes the
loop:

- **Detect** under-replicated segments: replicas on dead (or
  unregistered) and draining servers do not count against the table's
  target replication.
- **Grace window** (``PINOT_TPU_STABILIZE_GRACE_S``): a server's death
  only becomes actionable after the window, so a GC pause or a rolling
  bounce never triggers mass data movement.  Draining is deliberate
  operator intent and gets no grace.
- **Re-replicate** onto live tenant servers, least-loaded first with
  load measured in DOCS (not segment count), so placement stays
  balanced under skewed segment sizes (the skew-resistant-placement
  idea from PIM-tree, PAPERS.md).  The new replica is driven ONLINE
  through the normal transition path and re-fetches the segment from
  the controller's durable store copy.
- **Clean up** one round later: once the external view shows the
  target number of live ONLINE replicas, the dead/draining replicas
  drop out of the ideal state (DROPPED is sent only to live holders).
- **CONSUMING segments** are never copied (a consumer's rows are not
  durable): when every holder is unavailable the segment is retired and
  handed to ``RealtimeSegmentManager.ensure_consuming_segments``, which
  re-creates it on a live server resuming from the last COMMITTED
  offset.

Every action is a persisted ideal-state write, so the whole plan is
crash-idempotent: a controller killed mid-round recovers the
partially-applied ideal state from the property store and the next
round converges to the same fixpoint (add-phase is keyed on deficits,
drop-phase on restored coverage — both derived, never remembered).
"""
from __future__ import annotations

import logging
import os
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from pinot_tpu.controller.managers import _PeriodicManager
from pinot_tpu.controller.resource_manager import (
    CONSUMING,
    ClusterResourceManager,
    ONLINE,
)

logger = logging.getLogger(__name__)

_EVENT_RING = 256


class SelfStabilizer(_PeriodicManager):
    def __init__(
        self,
        resources: ClusterResourceManager,
        realtime_manager=None,
        interval_s: float = 2.0,
        grace_s: Optional[float] = None,
        now=None,
    ) -> None:
        super().__init__(interval_s, metrics_scope="stabilizer")
        self.resources = resources
        self.realtime_manager = realtime_manager
        if grace_s is None:
            grace_s = float(os.environ.get("PINOT_TPU_STABILIZE_GRACE_S", "5"))
        self.grace_s = grace_s
        self._now = now or time.monotonic
        # first-observed-dead timestamps; entries clear on recovery
        self._dead_since: Dict[str, float] = {}
        # heal-event ring for /debug/stabilizer and the dashboard (the
        # controller-side analog of the server's selfHealing counters)
        self._events: Deque[Dict[str, Any]] = deque(maxlen=_EVENT_RING)
        for m in (
            "stabilizer.rounds",
            "stabilizer.replicasAdded",
            "stabilizer.replicasDropped",
            "stabilizer.consumingReassigned",
            "stabilizer.graceDeferrals",
            "stabilizer.leaseDeferrals",
        ):
            self.metrics.meter(m)
        for g in (
            "stabilizer.underReplicatedSegments",
            "stabilizer.drainingInstances",
            "stabilizer.deadServers",
        ):
            self.metrics.gauge(g).set(0)

    # -- observability --------------------------------------------------
    def _event(self, kind: str, **fields: Any) -> None:
        self._events.append({"tsMs": int(time.time() * 1000), "event": kind, **fields})

    def events(self) -> List[Dict[str, Any]]:
        return list(self._events)

    def debug_snapshot(self) -> Dict[str, Any]:
        now = self._now()
        return {
            "graceSeconds": self.grace_s,
            "deadTracked": {
                name: round(now - since, 3)
                for name, since in sorted(self._dead_since.items())
            },
            "events": self.events(),
            "metrics": self.metrics.snapshot(),
        }

    def _quiescent(self, healthy, draining, server_state) -> bool:
        """Cheap precheck: True when no round work can possibly exist —
        nobody draining, every ideal-state replica sits on a healthy
        server, and every non-consuming segment meets its target
        replication.  One lock hold, no copies, no metadata reads."""
        if draining:
            return False
        res = self.resources
        with res._lock:
            for table, ideal in res.ideal_states.items():
                config = res.table_configs.get(table)
                if config is None or not ideal:
                    continue
                n_eligible = sum(
                    1
                    for s in healthy
                    if config.server_tenant in server_state[s][2]
                )
                n_target = min(config.replication, n_eligible)
                for replicas in ideal.values():
                    if not set(replicas) <= healthy:
                        return False
                    if (
                        CONSUMING not in replicas.values()
                        and len(replicas) < n_target
                    ):
                        return False
        return True

    # -- the convergence round -----------------------------------------
    def run_once(self) -> None:
        res = self.resources
        now = self._now()
        self.metrics.meter("stabilizer.rounds").mark()
        with res._lock:
            server_state = {
                n: (i.alive, i.draining, set(i.tags))
                for n, i in res.instances.items()
                if i.role == "server"
            }
            lease_until = {
                n: i.lease_until
                for n, i in res.instances.items()
                if i.role == "server"
            }
        healthy = {n for n, (a, d, _) in server_state.items() if a and not d}
        draining = {n for n, (a, d, _) in server_state.items() if a and d}

        def is_dead(s: str) -> bool:
            st = server_state.get(s)
            return st is None or not st[0]

        _actionable: Dict[str, bool] = {}

        def actionable_dead(s: str) -> bool:
            """Dead AND past the grace window (tracking starts at first
            observation, so a controller restarted mid-outage re-waits
            the window rather than acting on a stale clock) AND past its
            serving lease — a heartbeat-missing server whose lease has
            not expired may be alive-but-partitioned and still serving,
            so replicas move only after the lease window, never on a
            single missed heartbeat.  Memoized per round: the deferral
            meters count servers, not replicas."""
            if s in _actionable:
                return _actionable[s]
            if not is_dead(s):
                _actionable[s] = False
                return False
            since = self._dead_since.setdefault(s, now)
            if since == now:
                self._event("serverDead", server=s)
            ok = now - since >= self.grace_s
            if not ok:
                self.metrics.meter("stabilizer.graceDeferrals").mark()
            else:
                until = lease_until.get(s)
                if until is not None and now < until:
                    # lease fence: confirmed-dead is "lease expired";
                    # until then this is only "unreachable from here"
                    ok = False
                    self.metrics.meter("stabilizer.leaseDeferrals").mark()
                    self._event(
                        "leaseDeferral", server=s,
                        remainingS=round(until - now, 3),
                    )
            _actionable[s] = ok
            return ok

        # recoveries clear the death clock (a flap restarts the window)
        for s in [s for s in self._dead_since if not is_dead(s)]:
            del self._dead_since[s]
            self._event("serverRecovered", server=s)

        if self._quiescent(healthy, draining, server_state):
            # steady state: one lock hold over replica-set keys, no view
            # copies, no per-segment metadata reads — the 2s background
            # cadence must not contend with the serving path for nothing
            self.metrics.gauge("stabilizer.underReplicatedSegments").set(0)
            self.metrics.gauge("stabilizer.drainingInstances").set(0)
            self.metrics.gauge("stabilizer.deadServers").set(len(self._dead_since))
            return

        under_replicated = 0
        consuming_repair = False
        for table in res.tables():
            config = res.table_configs.get(table)
            if config is None:
                continue
            eligible = sorted(
                s for s in healthy if config.server_tenant in server_state[s][2]
            )
            ideal = res.get_ideal_state(table)
            if not ideal:
                continue
            n_target = min(config.replication, len(eligible))
            # doc-weighted load: a server holding one huge segment is
            # "fuller" than one holding three tiny ones (skew-resistant
            # placement) — counted over the ideal state incl. this
            # round's own additions
            def weight(seg: str) -> int:
                info = res.get_segment_metadata(table, seg)
                meta = info.get("metadata") if info else None
                docs = getattr(meta, "num_docs", 0) if meta is not None else 0
                return max(1, int(docs or 0))

            load = {s: 0 for s in eligible}
            for seg, replicas in ideal.items():
                w = weight(seg)
                for s in replicas:
                    if s in load:
                        load[s] += w
            view = res.get_external_view(table)
            for seg in sorted(ideal):
                replicas = ideal[seg]
                unavailable = [
                    s for s in replicas if s in draining or actionable_dead(s)
                ]
                if CONSUMING in replicas.values():
                    # a consumer's rows are not durable — never copy the
                    # segment; if NO holder is serving it, retire it so
                    # ensure_consuming_segments re-creates it on a live
                    # server at the last committed offset
                    if replicas and not (set(replicas) & healthy) and len(
                        unavailable
                    ) == len(replicas):
                        if self.realtime_manager is not None:
                            self.realtime_manager.release_segment_consumers(seg)
                        held = res.retire_segment(table, seg)
                        consuming_repair = True
                        self.metrics.meter("stabilizer.consumingReassigned").mark()
                        self._event(
                            "consumingRetired", table=table, segment=seg,
                            servers=held,
                        )
                    elif unavailable:
                        # a healthy holder keeps consuming: shed only the
                        # unavailable replicas (a drain would otherwise
                        # never report drained — the next sequence opens
                        # at full replication on live servers at commit).
                        # Transiently under-replicated, as the
                        # reference's fixed consuming assignment is too.
                        for s in unavailable:
                            if self.realtime_manager is not None:
                                self.realtime_manager.release_segment_consumers(
                                    seg, server=s
                                )
                            if res.remove_segment_replica(table, seg, s):
                                self.metrics.meter(
                                    "stabilizer.replicasDropped"
                                ).mark()
                                self._event(
                                    "replicaDropped", table=table, segment=seg,
                                    server=s, consuming=True,
                                    reason="draining" if s in draining else "dead",
                                )
                    continue
                if n_target == 0:
                    under_replicated += 1
                    continue
                # drop phase FIRST, using the pre-round external view: a
                # dead/draining replica leaves the ideal state only after
                # the view proves target-many live replicas serve the
                # segment (so the add phase of round N is confirmed by
                # the view before round N+1 drops anything)
                target_state = next(iter(replicas.values()), ONLINE)
                covered = [
                    s
                    for s, st in view.get(seg, {}).items()
                    if s in healthy and s in replicas and st == target_state
                ]
                if len(covered) >= n_target:
                    for s in unavailable:
                        if res.remove_segment_replica(table, seg, s):
                            self.metrics.meter("stabilizer.replicasDropped").mark()
                            self._event(
                                "replicaDropped", table=table, segment=seg,
                                server=s, reason="draining" if s in draining else "dead",
                            )
                            replicas.pop(s, None)
                # add phase: replicas within grace still count (that IS
                # the grace: no movement yet), draining/actionable ones
                # do not
                counted = [
                    s
                    for s in replicas
                    if s in healthy or (is_dead(s) and not actionable_dead(s))
                ]
                deficit = n_target - len(counted)
                if deficit <= 0:
                    continue
                under_replicated += 1
                w = weight(seg)
                candidates = [s for s in eligible if s not in replicas]
                for _ in range(deficit):
                    if not candidates:
                        break
                    pick = min(candidates, key=lambda s: (load[s], s))
                    candidates.remove(pick)
                    if res.add_segment_replica(table, seg, pick):
                        load[pick] += w
                        self.metrics.meter("stabilizer.replicasAdded").mark()
                        self._event(
                            "replicaAdded", table=table, segment=seg,
                            server=pick, docs=w,
                        )
        if consuming_repair and self.realtime_manager is not None:
            try:
                self.realtime_manager.ensure_consuming_segments()
            except Exception:
                logger.exception("consuming-segment repair failed")
        self.metrics.gauge("stabilizer.underReplicatedSegments").set(under_replicated)
        self.metrics.gauge("stabilizer.drainingInstances").set(len(draining))
        self.metrics.gauge("stabilizer.deadServers").set(len(self._dead_since))
