"""Controller segment store: durable copy of every uploaded segment.

The reference controller keeps the uploaded tar under its data dir and
serves it for server downloads (download URL in the segment's ZK
metadata; ``SegmentFetcherAndLoader.java:84`` re-downloads on CRC
mismatch).  Same contract here with a local directory per table.
"""
from __future__ import annotations

import os
import shutil
from typing import List, Optional

from pinot_tpu.segment.format import SEGMENT_FILE_NAME, read_segment, write_segment
from pinot_tpu.segment.immutable import ImmutableSegment


class SegmentStore:
    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def segment_dir(self, table: str, segment_name: str) -> str:
        return os.path.join(self.base_dir, table, segment_name)

    def save(self, table: str, segment: ImmutableSegment) -> str:
        d = self.segment_dir(table, segment.segment_name)
        write_segment(segment, d)
        return d

    def save_file(self, table: str, segment_name: str, src_path: str) -> str:
        d = self.segment_dir(table, segment_name)
        os.makedirs(d, exist_ok=True)
        shutil.copy(src_path, os.path.join(d, SEGMENT_FILE_NAME))
        return d

    def load(self, table: str, segment_name: str) -> ImmutableSegment:
        return read_segment(self.segment_dir(table, segment_name))

    def exists(self, table: str, segment_name: str) -> bool:
        return os.path.exists(
            os.path.join(self.segment_dir(table, segment_name), SEGMENT_FILE_NAME)
        )

    def delete(self, table: str, segment_name: str) -> None:
        d = self.segment_dir(table, segment_name)
        if os.path.exists(d):
            shutil.rmtree(d)

    def segment_size_bytes(self, table: str, segment_name: str) -> int:
        d = self.segment_dir(table, segment_name)
        total = 0
        for root, _dirs, files in os.walk(d):
            for f in files:
                total += os.path.getsize(os.path.join(root, f))
        return total

    def table_size_bytes(self, table: str) -> int:
        """Total on-disk bytes of the controller's durable copies for a
        table (the TableSizeResource / storage-quota input)."""
        d = os.path.join(self.base_dir, table)
        total = 0
        for root, _dirs, files in os.walk(d):
            for f in files:
                total += os.path.getsize(os.path.join(root, f))
        return total

    def list_segments(self, table: str) -> List[str]:
        d = os.path.join(self.base_dir, table)
        if not os.path.isdir(d):
            return []
        return sorted(os.listdir(d))
