"""Controller segment store: durable copy of every uploaded segment.

The reference controller keeps the uploaded tar under its data dir and
serves it for server downloads (download URL in the segment's ZK
metadata; ``SegmentFetcherAndLoader.java:84`` re-downloads on CRC
mismatch).  Same contract here with a local directory per table.
"""
from __future__ import annotations

import os
import shutil
import zlib
from typing import Dict, List, Optional

from pinot_tpu.segment.format import (
    SEGMENT_FILE_NAME,
    SegmentIntegrityError,
    read_segment,
    verify_segment_crc,
    write_segment,
)
from pinot_tpu.segment.immutable import ImmutableSegment


class SegmentStore:
    def __init__(self, base_dir: str) -> None:
        self.base_dir = base_dir
        os.makedirs(base_dir, exist_ok=True)

    def segment_dir(self, table: str, segment_name: str) -> str:
        return os.path.join(self.base_dir, table, segment_name)

    def save(self, table: str, segment: ImmutableSegment) -> str:
        d = self.segment_dir(table, segment.segment_name)
        write_segment(segment, d)
        return d

    def save_file(self, table: str, segment_name: str, src_path: str) -> str:
        d = self.segment_dir(table, segment_name)
        os.makedirs(d, exist_ok=True)
        shutil.copy(src_path, os.path.join(d, SEGMENT_FILE_NAME))
        return d

    def load(self, table: str, segment_name: str) -> ImmutableSegment:
        return read_segment(self.segment_dir(table, segment_name))

    def exists(self, table: str, segment_name: str) -> bool:
        return os.path.exists(
            os.path.join(self.segment_dir(table, segment_name), SEGMENT_FILE_NAME)
        )

    def delete(self, table: str, segment_name: str) -> None:
        d = self.segment_dir(table, segment_name)
        if os.path.exists(d):
            shutil.rmtree(d)

    def segment_size_bytes(self, table: str, segment_name: str) -> int:
        d = self.segment_dir(table, segment_name)
        total = 0
        for root, _dirs, files in os.walk(d):
            for f in files:
                total += os.path.getsize(os.path.join(root, f))
        return total

    def table_size_bytes(self, table: str) -> int:
        """Total on-disk bytes of the controller's durable copies for a
        table (the TableSizeResource / storage-quota input)."""
        d = os.path.join(self.base_dir, table)
        total = 0
        for root, _dirs, files in os.walk(d):
            for f in files:
                total += os.path.getsize(os.path.join(root, f))
        return total

    def list_segments(self, table: str) -> List[str]:
        d = os.path.join(self.base_dir, table)
        if not os.path.isdir(d):
            return []
        return sorted(os.listdir(d))

    def list_tables(self) -> List[str]:
        return sorted(
            t for t in os.listdir(self.base_dir)
            if os.path.isdir(os.path.join(self.base_dir, t))
        )

    def segment_file_path(self, table: str, segment_name: str) -> str:
        return os.path.join(self.segment_dir(table, segment_name), SEGMENT_FILE_NAME)

    def verify_copy(
        self, table: str, segment_name: str, expected_crc: Optional[int] = None
    ) -> ImmutableSegment:
        """Re-verify the durable copy (the deep-store scrub primitive).

        Raises ``FileNotFoundError`` for a lost copy and
        ``SegmentIntegrityError`` for an unreadable / CRC-failing one,
        or one whose verifiable CRC no longer matches the registered
        metadata (``expected_crc``)."""
        d = self.segment_dir(table, segment_name)
        path = os.path.join(d, SEGMENT_FILE_NAME)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        try:
            seg = read_segment(d)
        except SegmentIntegrityError:
            raise
        except Exception as e:
            raise SegmentIntegrityError(
                f"store copy {table}/{segment_name} unreadable: {e!r}"
            ) from e
        verify_segment_crc(seg, source=f"store:{table}/{segment_name}")
        if (
            expected_crc
            and seg.metadata.crc
            and seg.metadata.custom.get("dataCrc")
            and int(seg.metadata.crc) != int(expected_crc)
        ):
            raise SegmentIntegrityError(
                f"store copy {table}/{segment_name}: CRC {seg.metadata.crc} != "
                f"registered {expected_crc}"
            )
        return seg

    def save_bytes(self, table: str, segment_name: str, data: bytes) -> str:
        """Install raw segment-file bytes as the durable copy (reverse
        replication from a server), via tmp+rename so a concurrent
        download never sees a partial file."""
        d = self.segment_dir(table, segment_name)
        os.makedirs(d, exist_ok=True)
        dest = os.path.join(d, SEGMENT_FILE_NAME)
        tmp = dest + ".repair.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, dest)
        return d

    def file_crc32(self, table: str, segment_name: str) -> Optional[int]:
        """crc32 of the raw store file bytes (the backup-manifest
        fingerprint — byte-level, catches rot the header can't)."""
        path = self.segment_file_path(table, segment_name)
        try:
            with open(path, "rb") as f:
                return zlib.crc32(f.read()) & 0xFFFFFFFF
        except OSError:
            return None

    def manifest(self) -> Dict[str, Dict[str, Dict[str, int]]]:
        """{table: {segment: {sizeBytes, crc32}}} over every durable
        copy (the backup archive's segment manifest)."""
        out: Dict[str, Dict[str, Dict[str, int]]] = {}
        for table in self.list_tables():
            for seg in self.list_segments(table):
                path = self.segment_file_path(table, seg)
                if not os.path.exists(path):
                    continue
                crc = self.file_crc32(table, seg)
                out.setdefault(table, {})[seg] = {
                    "sizeBytes": os.path.getsize(path),
                    "crc32": crc if crc is not None else 0,
                }
        return out
